"""Bench-regression gate: diff BENCH_r*.json runs, flag regressions, exit
nonzero.

The r01→r05 trajectory (flagship 8.0x → 23.0x) has been folklore checked by
eyeball; this makes it a machine-checked invariant:

    python tools/bench_compare.py BENCH_r05.json BENCH_new.json
    python tools/bench_compare.py BENCH_r0*.json new.json   # trajectory too
    python tools/bench_compare.py --threshold \
        verify_commit_10k_sigs_per_sec=0.2 old.json new.json
    python tools/bench_compare.py --self-test

Accepted inputs: the driver's record format ({"tail": "<jsonl>", ...}), a
raw bench.py JSONL stream, or a JSON array of metric lines. The NEWEST file
(last argument) is gated against the one before it; earlier files only feed
the trajectory table.

Gating policy, by the bench's own unit conventions:
* throughput units (sigs/s, blocks/s, blocks/min): higher is better —
  regression when new < old * (1 - threshold);
* latency unit (s): lower is better — regression when
  new > old * (1 + threshold);
* informational units (ratio, events, ms/height, error) and *_failed
  markers: reported, never gated — EXCEPT the cost-structure ratios named
  in RATIO_GATED_LOWER_BETTER (currently the flagship's
  verify_commit_10k_breakdown_pack_share), which gate lower-is-better at
  the default threshold: the 7% -> 11.1% r04->r05 packing creep ran
  ungated and this is the regression gate that would have caught it.

The default threshold is deliberately loose (30%): the TPU relay's
effective bandwidth swings hour to hour (PROFILE_r05), and a gate that
cries wolf gets deleted. Tighten per-metric with --threshold NAME=FRAC.

Exit codes: 0 clean, 1 regression(s), 2 usage/parse error. Stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

DEFAULT_THRESHOLD = 0.30

#: units gated as higher-is-better throughput; "headers/s" is the
#: light-client serving plane's fleet-throughput unit (bench.py config
#: lightserve, tools/lightserve_bench.py); "commits/min" is the
#: degraded-network plane's WAN-profile throughput (bench.py config wan,
#: tools/quorum_loss.py)
HIGHER_BETTER_UNITS = {"sigs/s", "blocks/s", "blocks/min", "txs/s",
                       "commits/s", "commits/min", "headers/s"}
#: units gated as lower-is-better latency; "breaches" is the soak
#: plane's SLO-miss count (tools/soak.py) — more breaches is strictly
#: worse, same gating shape as a latency
LOWER_BETTER_UNITS = {"s", "ms", "breaches"}
#: ratio-unit metrics gated lower-is-better DESPITE ratios defaulting to
#: informational: the 10k flagship's packing share crept 7% -> 11.1%
#: r04 -> r05 with nothing watching — cost-structure creep in these trips
#: the gate like a latency regression would
RATIO_GATED_LOWER_BETTER = {"verify_commit_10k_breakdown_pack_share"}


def load_bench(path: str) -> Dict[str, dict]:
    """{metric: line} from a driver record, raw JSONL, or a JSON array.
    Later lines win (bench emits each metric once; reruns append)."""
    with open(path) as f:
        text = f.read()
    lines: List[str] = []
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        lines = str(doc["tail"]).splitlines()
    elif isinstance(doc, dict) and "metric" in doc:
        lines = [text]
    elif isinstance(doc, list):
        lines = [json.dumps(e) for e in doc]
    else:
        lines = text.splitlines()
    out: Dict[str, dict] = {}
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            out[rec["metric"]] = rec
    if not out:
        raise ValueError(f"{path}: no bench metric lines found")
    return out


def load_history(path: str):
    """(labels, runs) from a cross-run history file: one JSON object per
    line, ``{"label": ..., "metrics": [bench rows]}`` (tools/soak.py
    --history appends these). A bare list of rows is accepted too, with
    the line number as its label. Blank/comment lines are skipped."""
    labels: List[str] = []
    runs: List[Dict[str, dict]] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            doc = json.loads(line)
            if isinstance(doc, list):
                doc = {"label": f"run{i}", "metrics": doc}
            if not isinstance(doc, dict) or "metrics" not in doc:
                raise ValueError(
                    f"{path}:{i}: want {{'label', 'metrics'}} per line")
            run: Dict[str, dict] = {}
            for rec in doc["metrics"]:
                if isinstance(rec, dict) and "metric" in rec \
                        and "value" in rec:
                    run[rec["metric"]] = rec
            if not run:
                raise ValueError(f"{path}:{i}: no metric rows in entry")
            labels.append(str(doc.get("label", f"run{i}")))
            runs.append(run)
    if not runs:
        raise ValueError(f"{path}: empty history")
    return labels, runs


def gate_direction(metric: str, unit: str) -> Optional[str]:
    """'up' (higher better), 'down' (lower better), or None (not gated)."""
    if metric in RATIO_GATED_LOWER_BETTER and unit == "ratio":
        # checked before the generic _breakdown exclusion; the unit guard
        # keeps the crashed-config convention (unit "error") flagging the
        # row as errored instead of silently comparing garbage
        return "down"
    if metric.endswith("_failed") or "_breakdown" in metric \
            or metric == "trace_summary":
        return None
    if unit in HIGHER_BETTER_UNITS:
        return "up"
    if unit in LOWER_BETTER_UNITS:
        return "down"
    return None


def compare(old: Dict[str, dict], new: Dict[str, dict],
            thresholds: Dict[str, float],
            default_threshold: float = DEFAULT_THRESHOLD) -> List[dict]:
    """Per-metric verdicts for every metric in either run."""
    rows: List[dict] = []
    for metric in sorted(set(old) | set(new)):
        o, n = old.get(metric), new.get(metric)
        # direction comes from the OLD record's unit when it exists: a
        # crashed config re-emits its metric with unit "error" (bench.py's
        # except paths), and taking the new unit would silently un-gate it
        unit = (o or n).get("unit", "")
        direction = gate_direction(metric, unit)
        thr = thresholds.get(metric, default_threshold)
        row = {"metric": metric, "unit": unit,
               "old": o["value"] if o else None,
               "new": n["value"] if n else None,
               "direction": direction, "threshold": thr}
        if direction is None:
            if o is not None and n is not None and \
                    gate_direction(metric, n.get("unit", "")) is not None:
                # the REVERSE unit flip: the OLD record errored (direction
                # comes from its unit) while the new one gates — a crashed
                # baseline must not silently un-gate the metric; flag it so
                # the operator re-baselines instead of comparing garbage
                row["status"] = "errored"
            else:
                row["status"] = "info"
        elif o is None:
            row["status"] = "new"
        elif n is None:
            # the metric vanished — the config crashed or was deleted; a
            # silent disappearance must not read as "no regression"
            row["status"] = "missing"
        elif gate_direction(metric, n.get("unit", "")) != direction:
            # a gated metric flipped to a non-gated unit ("error"): the
            # config crashed — must not read as "no regression"
            row["status"] = "errored"
        else:
            ratio = (n["value"] / o["value"]) if o["value"] else float("inf")
            row["ratio"] = round(ratio, 3)
            if direction == "up":
                regressed = n["value"] < o["value"] * (1.0 - thr)
                improved = n["value"] > o["value"] * (1.0 + thr)
            else:
                regressed = n["value"] > o["value"] * (1.0 + thr)
                improved = n["value"] < o["value"] * (1.0 - thr)
            row["status"] = ("regressed" if regressed
                             else "improved" if improved else "ok")
        rows.append(row)
    return rows


def trajectory(runs: List[Dict[str, dict]], labels: List[str]) -> str:
    """metric × run table over every gated metric present anywhere."""
    metrics = sorted({m for run in runs for m in run
                      if gate_direction(m, run[m].get("unit", ""))
                      is not None})
    if not metrics:
        return "(no gated metrics)"
    w = max(len(m) for m in metrics)
    cols = [f"{lab[-14:]:>14}" for lab in labels]
    lines = [f"{'metric':<{w}}  " + "  ".join(cols)]
    for m in metrics:
        cells = []
        for run in runs:
            v = run.get(m, {}).get("value")
            cells.append(f"{v:>14.3f}" if isinstance(v, (int, float))
                         else f"{'-':>14}")
        lines.append(f"{m:<{w}}  " + "  ".join(cells))
    return "\n".join(lines)


def render(rows: List[dict]) -> str:
    w = max(len(r["metric"]) for r in rows)
    lines = [f"{'metric':<{w}}  {'old':>14}  {'new':>14}  {'ratio':>7}  "
             f"status"]
    for r in rows:
        old = f"{r['old']:.3f}" if isinstance(r["old"], (int, float)) else "-"
        new = f"{r['new']:.3f}" if isinstance(r["new"], (int, float)) else "-"
        ratio = f"{r['ratio']:.3f}" if "ratio" in r else "-"
        mark = {"regressed": " <-- REGRESSION",
                "missing": " <-- MISSING",
                "errored": " <-- ERRORED"}.get(r["status"], "")
        lines.append(f"{r['metric']:<{w}}  {old:>14}  {new:>14}  "
                     f"{ratio:>7}  {r['status']}{mark}")
    return "\n".join(lines)


def parse_thresholds(pairs: List[str]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for p in pairs:
        name, _, frac = p.partition("=")
        if not name or not frac:
            raise ValueError(f"--threshold wants NAME=FRACTION, got {p!r}")
        out[name] = float(frac)
    return out


# -- self-test ----------------------------------------------------------------

def _write(path: str, metrics: Dict[str, tuple]) -> None:
    with open(path, "w") as f:
        for m, (v, unit) in metrics.items():
            f.write(json.dumps({"metric": m, "value": v, "unit": unit,
                                "vs_baseline": 1.0}) + "\n")


def self_test() -> int:
    import os
    import tempfile

    d = tempfile.mkdtemp(prefix="bench-compare-")
    try:
        base = os.path.join(d, "old.json")
        _write(base, {"verify_commit_10k_sigs_per_sec": (157000.0, "sigs/s"),
                      "verify_commit_10k_multichip_sigs_per_sec":
                          (500000.0, "sigs/s"),
                      "localnet_4node_tx_commit_latency_p50": (1.1, "s"),
                      "localnet_4node_ingest_txs_per_sec": (24.0, "txs/s"),
                      "localnet_4node_ingest_commit_latency_p99_s":
                          (2.0, "s"),
                      "localnet_4node_ingest_checktx_p99_s": (0.02, "s"),
                      "verify_commit_10k_breakdown_pack_share":
                          (0.11, "ratio"),
                      "fast_sync_pipeline_breakdown_hash_store_share":
                          (0.2, "ratio")})
        # within the 30% window on throughput, latency, AND the gated
        # pack-share ratio: clean (other breakdown ratios stay info even
        # when they triple)
        ok = os.path.join(d, "ok.json")
        _write(ok, {"verify_commit_10k_sigs_per_sec": (140000.0, "sigs/s"),
                    "verify_commit_10k_multichip_sigs_per_sec":
                        (480000.0, "sigs/s"),
                    "localnet_4node_tx_commit_latency_p50": (1.3, "s"),
                    "localnet_4node_ingest_txs_per_sec": (22.0, "txs/s"),
                    "localnet_4node_ingest_commit_latency_p99_s":
                        (2.3, "s"),
                    "localnet_4node_ingest_checktx_p99_s": (0.024, "s"),
                    "verify_commit_10k_breakdown_pack_share":
                        (0.13, "ratio"),
                    "fast_sync_pipeline_breakdown_hash_store_share":
                        (0.6, "ratio")})
        assert main([base, ok]) == 0
        # the ingestion-plane rows gate like any throughput/latency pair:
        # a collapsed ingest rate (open-loop load no longer keeping up)
        # and a p99 blow-up each trip exit 1...
        ing_bad = os.path.join(d, "ingest_bad.json")
        _write(ing_bad, {"localnet_4node_ingest_txs_per_sec":
                         (10.0, "txs/s"),
                         "localnet_4node_ingest_commit_latency_p99_s":
                         (6.0, "s"),
                         "localnet_4node_ingest_checktx_p99_s":
                         (0.2, "s")})
        assert main(["--threshold", "verify_commit_10k_sigs_per_sec=9",
                     "--threshold",
                     "verify_commit_10k_multichip_sigs_per_sec=9",
                     "--threshold",
                     "localnet_4node_tx_commit_latency_p50=9",
                     "--threshold",
                     "verify_commit_10k_breakdown_pack_share=9",
                     base, ing_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(ing_bad), {})}
        assert rows["localnet_4node_ingest_txs_per_sec"][
            "status"] == "regressed"
        assert rows["localnet_4node_ingest_commit_latency_p99_s"][
            "status"] == "regressed"
        # the admission-latency row gates lower-better like any "s" metric:
        # a 10x checktx p99 blow-up trips on its own
        assert rows["localnet_4node_ingest_checktx_p99_s"][
            "status"] == "regressed"
        # (ing_bad also dropped the flagship rows — flagged as missing)
        assert rows["verify_commit_10k_sigs_per_sec"]["status"] == "missing"
        # ...a VANISHED ingest metric fails on its own...
        ing_gone = os.path.join(d, "ingest_gone.json")
        _write(ing_gone, {
            "verify_commit_10k_sigs_per_sec": (157000.0, "sigs/s"),
            "verify_commit_10k_multichip_sigs_per_sec":
                (500000.0, "sigs/s"),
            "localnet_4node_tx_commit_latency_p50": (1.1, "s"),
            "localnet_4node_ingest_txs_per_sec": (24.0, "txs/s"),
            "verify_commit_10k_breakdown_pack_share": (0.11, "ratio"),
        })
        assert main([base, ing_gone]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(ing_gone), {})}
        assert rows["localnet_4node_ingest_commit_latency_p99_s"][
            "status"] == "missing"
        # ...and per-metric threshold overrides loosen both ingest gates
        assert main(["--threshold", "localnet_4node_ingest_txs_per_sec=0.9",
                     "--threshold",
                     "localnet_4node_ingest_commit_latency_p99_s=9",
                     "--threshold", "verify_commit_10k_sigs_per_sec=9",
                     "--threshold",
                     "verify_commit_10k_multichip_sigs_per_sec=9",
                     "--threshold",
                     "localnet_4node_tx_commit_latency_p50=9",
                     "--threshold",
                     "verify_commit_10k_breakdown_pack_share=9",
                     base, ing_bad]) == 1  # missing flagships still fail
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(ing_bad),
            {"localnet_4node_ingest_txs_per_sec": 0.9,
             "localnet_4node_ingest_commit_latency_p99_s": 9.0})}
        assert rows["localnet_4node_ingest_txs_per_sec"]["status"] == "ok"
        assert rows["localnet_4node_ingest_commit_latency_p99_s"][
            "status"] == "ok"
        # flagship degraded 60%: gate trips — and the MULTICHIP flagship
        # is gated higher-better exactly like it (a silently-collapsed
        # device pool reads as a regression, not noise)
        bad = os.path.join(d, "bad.json")
        _write(bad, {"verify_commit_10k_sigs_per_sec": (60000.0, "sigs/s"),
                     "verify_commit_10k_multichip_sigs_per_sec":
                         (150000.0, "sigs/s"),
                     "localnet_4node_tx_commit_latency_p50": (1.0, "s"),
                     "localnet_4node_ingest_txs_per_sec": (24.0, "txs/s"),
                     "localnet_4node_ingest_commit_latency_p99_s":
                         (2.0, "s"),
                     "localnet_4node_ingest_checktx_p99_s": (0.02, "s"),
                     "verify_commit_10k_breakdown_pack_share":
                         (0.11, "ratio")})
        assert main([base, bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(bad), {})}
        assert rows["verify_commit_10k_multichip_sigs_per_sec"][
            "status"] == "regressed"
        # the r04 -> r05 packing-share creep (0.07 -> 0.111, +59%), replayed
        # synthetically: lower-is-better ratio gating trips exit 1
        creep_old = os.path.join(d, "creep_old.json")
        creep_new = os.path.join(d, "creep_new.json")
        _write(creep_old, {"verify_commit_10k_breakdown_pack_share":
                           (0.07, "ratio")})
        _write(creep_new, {"verify_commit_10k_breakdown_pack_share":
                           (0.111, "ratio")})
        assert main([creep_old, creep_new]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(creep_old), load_bench(creep_new), {})}
        assert rows["verify_commit_10k_breakdown_pack_share"][
            "status"] == "regressed"
        # ...and a loosened per-metric threshold un-trips it
        assert main(["--threshold",
                     "verify_commit_10k_breakdown_pack_share=0.9",
                     creep_old, creep_new]) == 0
        # an ERRORED BASELINE must not silently un-gate the metric for the
        # next run (reverse unit flip: old=error, new=ratio)
        err_base = os.path.join(d, "err_base.json")
        _write(err_base, {"verify_commit_10k_breakdown_pack_share":
                          (0.0, "error")})
        assert main([err_base, creep_new]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(err_base), load_bench(creep_new), {})}
        assert rows["verify_commit_10k_breakdown_pack_share"][
            "status"] == "errored"
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(bad), {})}
        assert rows["verify_commit_10k_sigs_per_sec"]["status"] == "regressed"
        # latency is gated lower-is-better
        slow = os.path.join(d, "slow.json")
        _write(slow, {"verify_commit_10k_sigs_per_sec": (157000.0, "sigs/s"),
                      "localnet_4node_tx_commit_latency_p50": (2.0, "s"),
                      "verify_commit_10k_breakdown_pack_share":
                          (0.11, "ratio")})
        assert main([base, slow]) == 1
        # a VANISHED gated metric is a failure, an informational one is not
        gone = os.path.join(d, "gone.json")
        _write(gone, {"localnet_4node_tx_commit_latency_p50": (1.1, "s")})
        assert main([base, gone]) == 1
        # a gated metric re-emitted with unit "error" (bench's crashed-
        # config convention) is a failure, not an un-gated info row
        err = os.path.join(d, "err.json")
        _write(err, {"verify_commit_10k_sigs_per_sec": (0.0, "error"),
                     "localnet_4node_tx_commit_latency_p50": (1.1, "s")})
        assert main([base, err]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(base), load_bench(err), {})}
        assert rows["verify_commit_10k_sigs_per_sec"]["status"] == "errored"
        # per-metric threshold override loosens the gate
        assert main(["--threshold", "verify_commit_10k_sigs_per_sec=0.9",
                     "--threshold",
                     "verify_commit_10k_multichip_sigs_per_sec=0.9",
                     "--threshold",
                     "localnet_4node_tx_commit_latency_p50=2.0",
                     base, bad]) == 0
        # the churn-plane rows gate like any throughput/latency pair: a
        # collapsed blocks/min under churn and a join-to-caught-up blow-up
        # each trip exit 1, a vanished row fails on its own, and per-metric
        # threshold overrides loosen both gates
        ch_base = os.path.join(d, "churn_base.json")
        _write(ch_base, {"inproc_churn8_blocks_per_min":
                         (14.0, "blocks/min"),
                         "inproc_churn8_join_caughtup_s": (8.0, "s")})
        ch_bad = os.path.join(d, "churn_bad.json")
        _write(ch_bad, {"inproc_churn8_blocks_per_min": (5.0, "blocks/min"),
                        "inproc_churn8_join_caughtup_s": (30.0, "s")})
        assert main([ch_base, ch_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ch_base), load_bench(ch_bad), {})}
        assert rows["inproc_churn8_blocks_per_min"]["status"] == "regressed"
        assert rows["inproc_churn8_join_caughtup_s"]["status"] == "regressed"
        ch_gone = os.path.join(d, "churn_gone.json")
        _write(ch_gone, {"inproc_churn8_blocks_per_min":
                         (14.0, "blocks/min")})
        assert main([ch_base, ch_gone]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ch_base), load_bench(ch_gone), {})}
        assert rows["inproc_churn8_join_caughtup_s"]["status"] == "missing"
        assert main(["--threshold", "inproc_churn8_blocks_per_min=0.9",
                     "--threshold", "inproc_churn8_join_caughtup_s=9",
                     ch_base, ch_bad]) == 0
        # a crashed churn config re-emits its rows with unit "error":
        # flagged errored, never silently un-gated
        ch_err = os.path.join(d, "churn_err.json")
        _write(ch_err, {"inproc_churn8_blocks_per_min": (0.0, "error"),
                        "inproc_churn8_join_caughtup_s": (8.0, "s")})
        assert main([ch_base, ch_err]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ch_base), load_bench(ch_err), {})}
        assert rows["inproc_churn8_blocks_per_min"]["status"] == "errored"
        # the scaling breakdown stays informational (never gated)
        assert gate_direction("inproc_churn_gossip_scaling_breakdown",
                              "ratio") is None
        # the crash-recovery row gates lower-better in BOTH directions: a
        # kill→caught-up blow-up regresses, a big speedup reads improved,
        # a vanished row fails, and a crashed config reads errored
        cr_base = os.path.join(d, "crash_base.json")
        _write(cr_base, {"inproc_crash4_kill_caughtup_s": (5.0, "s")})
        cr_bad = os.path.join(d, "crash_bad.json")
        _write(cr_bad, {"inproc_crash4_kill_caughtup_s": (20.0, "s")})
        assert main([cr_base, cr_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(cr_base), load_bench(cr_bad), {})}
        assert rows["inproc_crash4_kill_caughtup_s"][
            "status"] == "regressed"
        cr_fast = os.path.join(d, "crash_fast.json")
        _write(cr_fast, {"inproc_crash4_kill_caughtup_s": (2.0, "s")})
        rows = {r["metric"]: r for r in compare(
            load_bench(cr_base), load_bench(cr_fast), {})}
        assert rows["inproc_crash4_kill_caughtup_s"]["status"] == "improved"
        assert main([cr_base, cr_fast]) == 0
        cr_gone = os.path.join(d, "crash_gone.json")
        _write(cr_gone, {"unrelated_row": (1.0, "s")})
        assert main([cr_base, cr_gone]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(cr_base), load_bench(cr_gone), {})}
        assert rows["inproc_crash4_kill_caughtup_s"]["status"] == "missing"
        cr_err = os.path.join(d, "crash_err.json")
        _write(cr_err, {"inproc_crash4_kill_caughtup_s": (0.0, "error")})
        assert main([cr_base, cr_err]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(cr_base), load_bench(cr_err), {})}
        assert rows["inproc_crash4_kill_caughtup_s"]["status"] == "errored"
        # ...and a loosened per-metric threshold un-trips the regression
        assert main(["--threshold", "inproc_crash4_kill_caughtup_s=9",
                     cr_base, cr_bad]) == 0
        # the exec A/B row gates higher-better in BOTH directions: a
        # committed-throughput collapse regresses, a jump reads improved
        ex_base = os.path.join(d, "exec_base.json")
        _write(ex_base, {"inproc_exec4_committed_txs_per_sec":
                         (100.0, "txs/s")})
        ex_bad = os.path.join(d, "exec_bad.json")
        _write(ex_bad, {"inproc_exec4_committed_txs_per_sec":
                        (40.0, "txs/s")})
        assert main([ex_base, ex_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ex_base), load_bench(ex_bad), {})}
        assert rows["inproc_exec4_committed_txs_per_sec"][
            "status"] == "regressed"
        ex_fast = os.path.join(d, "exec_fast.json")
        _write(ex_fast, {"inproc_exec4_committed_txs_per_sec":
                         (250.0, "txs/s")})
        assert main([ex_base, ex_fast]) == 0
        rows = {r["metric"]: r for r in compare(
            load_bench(ex_base), load_bench(ex_fast), {})}
        assert rows["inproc_exec4_committed_txs_per_sec"][
            "status"] == "improved"
        # ...while the exec phase breakdown stays informational
        assert gate_direction("inproc_exec4_phase_breakdown",
                              "ratio") is None
        # the aggregate-signature A/B rows gate higher-better in BOTH
        # directions on the commits/s unit: a collapsed BLS verify rate
        # regresses, a jump reads improved, and the informational
        # commit-size row (unit "bytes") never gates
        ag_base = os.path.join(d, "aggsig_base.json")
        _write(ag_base, {
            "verify_commit_1000val_ed25519_batched_commits_per_sec":
                (3.0, "commits/s"),
            "verify_commit_1000val_bls_aggregated_commits_per_sec":
                (16.0, "commits/s"),
            "aggregated_commit_1000val_bytes": (190.0, "bytes")})
        ag_bad = os.path.join(d, "aggsig_bad.json")
        _write(ag_bad, {
            "verify_commit_1000val_ed25519_batched_commits_per_sec":
                (3.0, "commits/s"),
            "verify_commit_1000val_bls_aggregated_commits_per_sec":
                (4.0, "commits/s"),
            "aggregated_commit_1000val_bytes": (700.0, "bytes")})
        assert main([ag_base, ag_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ag_base), load_bench(ag_bad), {})}
        assert rows["verify_commit_1000val_bls_aggregated_commits_per_sec"][
            "status"] == "regressed"
        assert rows["aggregated_commit_1000val_bytes"]["status"] == "info"
        ag_fast = os.path.join(d, "aggsig_fast.json")
        _write(ag_fast, {
            "verify_commit_1000val_ed25519_batched_commits_per_sec":
                (3.0, "commits/s"),
            "verify_commit_1000val_bls_aggregated_commits_per_sec":
                (40.0, "commits/s"),
            "aggregated_commit_1000val_bytes": (190.0, "bytes")})
        assert main([ag_base, ag_fast]) == 0
        rows = {r["metric"]: r for r in compare(
            load_bench(ag_base), load_bench(ag_fast), {})}
        assert rows["verify_commit_1000val_bls_aggregated_commits_per_sec"][
            "status"] == "improved"
        # ...and the loosened per-metric threshold un-trips the regression
        assert main([
            "--threshold",
            "verify_commit_1000val_bls_aggregated_commits_per_sec=0.9",
            ag_base, ag_bad]) == 0
        # the soak rows: the "breaches" unit gates lower-better in BOTH
        # directions — more SLO misses regress, fewer read improved —
        # and missing/errored rows trip like any gated metric
        assert gate_direction("inproc_soak_slo_breaches",
                              "breaches") == "down"
        so_base = os.path.join(d, "soak_base.json")
        _write(so_base, {"inproc_soak_slo_breaches": (2.0, "breaches"),
                         "inproc_soak_commit_p99_s": (6.0, "s")})
        so_bad = os.path.join(d, "soak_bad.json")
        _write(so_bad, {"inproc_soak_slo_breaches": (9.0, "breaches"),
                        "inproc_soak_commit_p99_s": (6.0, "s")})
        assert main([so_base, so_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(so_base), load_bench(so_bad), {})}
        assert rows["inproc_soak_slo_breaches"]["status"] == "regressed"
        so_good = os.path.join(d, "soak_good.json")
        _write(so_good, {"inproc_soak_slo_breaches": (0.0, "breaches"),
                         "inproc_soak_commit_p99_s": (5.5, "s")})
        assert main([so_base, so_good]) == 0
        rows = {r["metric"]: r for r in compare(
            load_bench(so_base), load_bench(so_good), {})}
        assert rows["inproc_soak_slo_breaches"]["status"] == "improved"
        so_gone = os.path.join(d, "soak_gone.json")
        _write(so_gone, {"inproc_soak_commit_p99_s": (6.0, "s")})
        rows = {r["metric"]: r for r in compare(
            load_bench(so_base), load_bench(so_gone), {})}
        assert rows["inproc_soak_slo_breaches"]["status"] == "missing"
        assert main([so_base, so_gone]) == 1
        so_err = os.path.join(d, "soak_err.json")
        _write(so_err, {"inproc_soak_slo_breaches": (0.0, "error"),
                        "inproc_soak_commit_p99_s": (6.0, "s")})
        rows = {r["metric"]: r for r in compare(
            load_bench(so_base), load_bench(so_err), {})}
        assert rows["inproc_soak_slo_breaches"]["status"] == "errored"
        assert main([so_base, so_err]) == 1
        # ...and a loosened per-metric threshold un-trips the soak gate
        assert main(["--threshold", "inproc_soak_slo_breaches=4",
                     so_base, so_bad]) == 0
        # the light-client serving rows gate BOTH directions: the fleet
        # throughput ("headers/s") higher-better, the client p99 ("s")
        # lower-better — a collapsed coalescer regresses on either axis,
        # a faster one reads improved, and the crashed-config convention
        # (unit "error") trips rather than un-gates
        assert gate_direction("lightserve_clients_headers_per_sec",
                              "headers/s") == "up"
        assert gate_direction("lightserve_p99_s", "s") == "down"
        ls_base = os.path.join(d, "lightserve_base.json")
        _write(ls_base, {"lightserve_clients_headers_per_sec":
                         (2000.0, "headers/s"),
                         "lightserve_p99_s": (0.010, "s"),
                         "lightserve_bls_clients_headers_per_sec":
                         (400.0, "headers/s")})
        ls_bad = os.path.join(d, "lightserve_bad.json")
        _write(ls_bad, {"lightserve_clients_headers_per_sec":
                        (800.0, "headers/s"),
                        "lightserve_p99_s": (0.050, "s"),
                        "lightserve_bls_clients_headers_per_sec":
                        (400.0, "headers/s")})
        assert main([ls_base, ls_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ls_base), load_bench(ls_bad), {})}
        assert rows["lightserve_clients_headers_per_sec"][
            "status"] == "regressed"
        assert rows["lightserve_p99_s"]["status"] == "regressed"
        ls_fast = os.path.join(d, "lightserve_fast.json")
        _write(ls_fast, {"lightserve_clients_headers_per_sec":
                         (3500.0, "headers/s"),
                         "lightserve_p99_s": (0.004, "s"),
                         "lightserve_bls_clients_headers_per_sec":
                         (700.0, "headers/s")})
        assert main([ls_base, ls_fast]) == 0
        rows = {r["metric"]: r for r in compare(
            load_bench(ls_base), load_bench(ls_fast), {})}
        assert rows["lightserve_clients_headers_per_sec"][
            "status"] == "improved"
        assert rows["lightserve_p99_s"]["status"] == "improved"
        ls_gone = os.path.join(d, "lightserve_gone.json")
        _write(ls_gone, {"lightserve_p99_s": (0.010, "s")})
        assert main([ls_base, ls_gone]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ls_base), load_bench(ls_gone), {})}
        assert rows["lightserve_clients_headers_per_sec"][
            "status"] == "missing"
        ls_err = os.path.join(d, "lightserve_err.json")
        _write(ls_err, {"lightserve_clients_headers_per_sec":
                        (0.0, "error"),
                        "lightserve_p99_s": (0.010, "s"),
                        "lightserve_bls_clients_headers_per_sec":
                        (400.0, "headers/s")})
        assert main([ls_base, ls_err]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(ls_base), load_bench(ls_err), {})}
        assert rows["lightserve_clients_headers_per_sec"][
            "status"] == "errored"
        # ...and loosened per-metric thresholds un-trip the pair
        assert main(["--threshold",
                     "lightserve_clients_headers_per_sec=0.9",
                     "--threshold", "lightserve_p99_s=9",
                     ls_base, ls_bad]) == 0
        # the degraded-network rows (bench.py config wan): WAN-profile
        # throughput ("commits/min") gates higher-better, quorum-loss
        # recovery ("s") lower-better — both directions trip, both read
        # improved when they move the right way, and the crashed-config
        # convention (unit "error") trips rather than un-gates
        assert gate_direction("inproc_wan4_commits_per_min",
                              "commits/min") == "up"
        assert gate_direction("inproc_quorumloss_recover_s", "s") == "down"
        wn_base = os.path.join(d, "wan_base.json")
        _write(wn_base, {"inproc_wan4_commits_per_min":
                         (28.0, "commits/min"),
                         "inproc_quorumloss_recover_s": (2.0, "s")})
        wn_bad = os.path.join(d, "wan_bad.json")
        _write(wn_bad, {"inproc_wan4_commits_per_min":
                        (12.0, "commits/min"),
                        "inproc_quorumloss_recover_s": (9.0, "s")})
        assert main([wn_base, wn_bad]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(wn_base), load_bench(wn_bad), {})}
        assert rows["inproc_wan4_commits_per_min"]["status"] == "regressed"
        assert rows["inproc_quorumloss_recover_s"]["status"] == "regressed"
        wn_good = os.path.join(d, "wan_good.json")
        _write(wn_good, {"inproc_wan4_commits_per_min":
                         (45.0, "commits/min"),
                         "inproc_quorumloss_recover_s": (1.0, "s")})
        assert main([wn_base, wn_good]) == 0
        rows = {r["metric"]: r for r in compare(
            load_bench(wn_base), load_bench(wn_good), {})}
        assert rows["inproc_wan4_commits_per_min"]["status"] == "improved"
        assert rows["inproc_quorumloss_recover_s"]["status"] == "improved"
        wn_err = os.path.join(d, "wan_err.json")
        _write(wn_err, {"inproc_wan4_commits_per_min": (0.0, "error"),
                        "inproc_quorumloss_recover_s": (2.0, "s")})
        assert main([wn_base, wn_err]) == 1
        rows = {r["metric"]: r for r in compare(
            load_bench(wn_base), load_bench(wn_err), {})}
        assert rows["inproc_wan4_commits_per_min"]["status"] == "errored"
        # ...and loosened per-metric thresholds un-trip the pair
        assert main(["--threshold", "inproc_wan4_commits_per_min=0.9",
                     "--threshold", "inproc_quorumloss_recover_s=9",
                     wn_base, wn_bad]) == 0
        # cross-run history (--history): the JSONL trend file soak.py
        # appends to — the newest entry gates against the one before it,
        # a drifting trend exits 1, an improving one exits 0, and a
        # single entry has nothing to gate yet
        hist_bad = os.path.join(d, "hist_bad.jsonl")
        with open(hist_bad, "w") as f:
            for label, breaches, p99 in (("r01", 0.0, 5.0),
                                         ("r02", 1.0, 5.5),
                                         ("r03", 6.0, 9.0)):
                f.write(json.dumps({"label": label, "metrics": [
                    {"metric": "inproc_soak_slo_breaches",
                     "value": breaches, "unit": "breaches"},
                    {"metric": "inproc_soak_commit_p99_s",
                     "value": p99, "unit": "s"}]}) + "\n")
        assert main(["--history", hist_bad]) == 1
        labels, runs = load_history(hist_bad)
        assert labels == ["r01", "r02", "r03"]
        rows = {r["metric"]: r for r in compare(runs[-2], runs[-1], {})}
        assert rows["inproc_soak_slo_breaches"]["status"] == "regressed"
        assert rows["inproc_soak_commit_p99_s"]["status"] == "regressed"
        table = trajectory(runs, labels)
        assert "inproc_soak_slo_breaches" in table and "r03" in table
        hist_ok = os.path.join(d, "hist_ok.jsonl")
        with open(hist_ok, "w") as f:
            for label, breaches in (("r01", 6.0), ("r02", 2.0),
                                    ("r03", 1.0)):
                f.write(json.dumps({"label": label, "metrics": [
                    {"metric": "inproc_soak_slo_breaches",
                     "value": breaches, "unit": "breaches"}]}) + "\n")
        assert main(["--history", hist_ok]) == 0
        hist_one = os.path.join(d, "hist_one.jsonl")
        with open(hist_one, "w") as f:
            f.write(json.dumps({"label": "r01", "metrics": [
                {"metric": "inproc_soak_slo_breaches",
                 "value": 0.0, "unit": "breaches"}]}) + "\n")
        assert main(["--history", hist_one]) == 0
        # a bare row list per line is accepted with generated labels
        hist_bare = os.path.join(d, "hist_bare.jsonl")
        with open(hist_bare, "w") as f:
            f.write(json.dumps([{"metric": "lightserve_p99_s",
                                 "value": 0.01, "unit": "s"}]) + "\n")
            f.write(json.dumps([{"metric": "lightserve_p99_s",
                                 "value": 0.09, "unit": "s"}]) + "\n")
        assert main(["--history", hist_bare]) == 1
        # the driver's record format ({"tail": jsonl}) parses identically
        drv = os.path.join(d, "driver.json")
        with open(drv, "w") as f:
            json.dump({"n": 5, "rc": 0, "tail": "noise\n" + json.dumps(
                {"metric": "verify_commit_10k_sigs_per_sec",
                 "value": 150000.0, "unit": "sigs/s",
                 "vs_baseline": 22.0}) + "\n"}, f)
        assert load_bench(drv)[
            "verify_commit_10k_sigs_per_sec"]["value"] == 150000.0
        assert main([drv, ok]) == 0
        # trajectory across 3 runs renders every gated metric — including
        # the now-gated pack share, but not the informational ratios
        table = trajectory([load_bench(p) for p in (base, ok, bad)],
                           ["r01", "r02", "r03"])
        assert "verify_commit_10k_sigs_per_sec" in table
        assert "verify_commit_10k_breakdown_pack_share" in table
        assert "fast_sync_pipeline_breakdown_hash_store_share" not in table
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)
    print("bench_compare self-test OK (gates, thresholds, formats, "
          "history trends)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("runs", nargs="*",
                    help="bench result files, oldest first; the last is "
                         "gated against the one before it")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="per-metric regression threshold (repeatable)")
    ap.add_argument("--default-threshold", type=float,
                    default=DEFAULT_THRESHOLD)
    ap.add_argument("--json", action="store_true",
                    help="print the comparison rows as JSON")
    ap.add_argument("--history", metavar="PATH",
                    help="cross-run history file (JSONL, one run per "
                         "line; tools/soak.py --history appends these): "
                         "render the whole trajectory and gate the "
                         "newest entry against the one before it")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    try:
        thresholds = parse_thresholds(args.threshold)
        if args.history:
            if args.runs:
                ap.error("--history takes no positional run files")
            labels, runs = load_history(args.history)
        else:
            if len(args.runs) < 2:
                ap.error("need at least two run files "
                         "(or --history / --self-test)")
            labels, runs = list(args.runs), [load_bench(p)
                                             for p in args.runs]
    except (ValueError, OSError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    if len(runs) < 2:
        # a one-entry history has nothing to gate yet: render it and
        # leave clean — the SECOND run is when the trend line starts
        print(trajectory(runs, labels))
        print("\nOK: single history entry, nothing to gate yet")
        return 0
    rows = compare(runs[-2], runs[-1], thresholds, args.default_threshold)
    bad = [r for r in rows
           if r["status"] in ("regressed", "missing", "errored")]
    if args.json:
        print(json.dumps({"rows": rows, "regressions": len(bad)}, indent=2))
        return 1 if bad else 0
    if len(runs) > 2:
        print(trajectory(runs, labels))
        print()
    print(render(rows))
    print()
    if bad:
        print(f"FAIL: {len(bad)} regression(s) beyond threshold: "
              + ", ".join(r["metric"] for r in bad))
        return 1
    print(f"OK: no regressions across {sum(1 for r in rows if r['direction'])}"
          " gated metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
