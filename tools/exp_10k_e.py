"""Timeline instrumentation of the segmented pipeline + fetch batching test."""

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from bench import _mk_val_set, _sign_commit
from tendermint_tpu.crypto.ed25519_jax import verify as V


def main():
    n_vals, n_commits = 10240, 6
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    pks, msgs, sigs = [], [], []
    for c in commits:
        pks += [v.pub_key.bytes() for v in vs.validators]
        msgs += [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs += [cs.signature for cs in c.signatures]
    n = len(pks)
    pool = ThreadPoolExecutor(max_workers=2)
    print("setup done", flush=True)

    segs = [(0, 20480), (20480, 40960), (40960, 61440)]

    def run(fetch_mode):
        t_start = time.perf_counter()
        ev = []

        def submit(a, b):
            t0 = time.perf_counter() - t_start
            args, ok = V.prepare_sparse_stream(pks[a:b], msgs[a:b],
                                               sigs[a:b], 2048)
            t1 = time.perf_counter() - t_start
            dev = V._verify_sparse_stream_kernel(*args)
            t2 = time.perf_counter() - t_start
            ev.append(("pack+disp", a, round(t0 * 1e3), round(t1 * 1e3),
                       round(t2 * 1e3)))
            return dev, ok

        futs = [pool.submit(submit, a, b) for a, b in segs]
        if fetch_mode == "per-seg":
            for i, f in enumerate(futs):
                dev, ok = f.result()
                t0 = time.perf_counter() - t_start
                out = np.asarray(dev)
                t1 = time.perf_counter() - t_start
                ev.append(("fetch", i, round(t0 * 1e3), round(t1 * 1e3)))
                assert out.reshape(-1).all() and ok.all()
        else:
            devs = [f.result() for f in futs]
            t0 = time.perf_counter() - t_start
            outs = jax.device_get([d for d, _ in devs])
            t1 = time.perf_counter() - t_start
            ev.append(("batched-fetch", -1, round(t0 * 1e3), round(t1 * 1e3)))
            for (d, ok), out in zip(devs, outs):
                assert np.asarray(out).reshape(-1).all() and ok.all()
        total = time.perf_counter() - t_start
        return total, ev

    run("per-seg")  # warm
    for mode in ("per-seg", "batched", "per-seg", "batched"):
        total, ev = run(mode)
        print(f"{mode:8s} total {total*1e3:7.1f} ms -> {n/total:8.0f} sigs/s")
        for e in ev:
            print("   ", e)
        sys.stdout.flush()


if __name__ == "__main__":
    main()
