"""Seeded chaos matrix: every fault site × several seeds, pass/fail table.

Each cell runs in a FRESH subprocess (fault plane, breaker, and fail-point
state are process-global by design) and exercises one injection site with a
deterministic seed, asserting the survival property that site promises:

* device.batch_verify — injected device errors: host fallback keeps
  verdicts byte-identical, breaker opens and re-closes
* device.lane         — ONE device label armed (device.lane.<label>): the
  multi-device pool degrades to the healthy lanes, re-shards the sick
  lane's segments with zero dropped signatures, verdicts byte-identical;
  a healed lane rejoins
* device.vote_flush   — same through the vote micro-batcher (futures all
  resolve correctly, no device error ever surfaces)
* wal.fsync           — fsync EIO (policy=raise here): records past the
  last good fsync may be lost, records before it NEVER; replay stays clean
* db.write_batch      — BufferedDB flush fault: staged window preserved,
  retry after disarm lands every record (no handled-but-not-durable)
* net.drop            — 4-node in-proc net commits +3 heights under seeded
  10% loss with identical block hashes (the slow cell, ~30-60s)
* ingest.mempool_full — open-loop tx load (loadtime schedule) into a
  validator with an 8-slot mempool while another validator is partitioned
  away: reason="full" rejections fire, the tx lifecycle ring stays
  bounded, honest 3/4 keep committing hash-identical blocks
* ingest.backpressure — open-loop overload through the ASYNC admission
  pipeline (mempool/ingest.py) against a 16-slot intake queue on a
  sharded-lane mempool, one validator partitioned away: reason-labeled
  sheds fire (queue-full), every shed comes back as an explicit
  rejection (never a stall), the intake queue never exceeds its bound,
  honest 3/4 keep committing hash-identical blocks

Adversarial (content-corruption) cells — the Byzantine chaos suite:

* net.corrupt              — 4-node net stays live and hash-identical while
  a capped 10% of in-flight payloads get a bit flipped (receivers drop the
  corrupting link; persistent-peer-style reconnects re-heal it); injection
  count replays exactly for a seed
* statesync.lying_chunk    — a restore served by honest peers + one liar
  completes anyway: per-chunk verification strikes the liar, bans it after
  K bad chunks, refetches from honest peers
* statesync.lying_snapshot — a snapshot advertised with a bogus hash is
  restored, fails the trusted-app-hash check, its advertiser is struck,
  and re-discovery finds the honest snapshot
* blocksync.bad_block      — a fresh node fast-syncs a chain although its
  providers serve a capped number of tampered block responses (redo +
  scoreboard backoff/ban)
* combo.maverick_corrupt   — double-prevoting validator AND corrupt links
  at once; honest nodes agree (the slow combo cell)

Churn cells — membership change as the fault (tools/churn.py rig):

* churn.flap        — one node leaves and re-joins 3 times (fresh stores:
  every re-entry is a full statesync restore over the wire); survivors
  never redial the departed id, every rejoin reaches caught-up, hashes
  stay identical
* churn.rotate      — the full churn schedule at N=8 under open-loop load:
  one statesync join + one clean leave per interval, the validator set
  rotating via kvstore val: txs across app-driven prune boundaries;
  survivor app-hash agreement, every retained height's validator set
  resolves, AddrBook/peerscore state bounded
* churn.partition32 — the partition cell re-run at scale: a 32-node SPARSE
  net (4 validators + 28 fulls, ring+chords degree 4) has 8 nodes
  blackholed, the majority keeps committing, heal reconverges everyone to
  identical hashes
* churn.corrupt32   — the corruption cell re-run at scale: the 32-node
  sparse net survives capped bit flips on in-flight payloads (receivers
  drop corrupting links, the redial loop re-heals), hashes identical

Degraded-network cells — the hard regimes of partial synchrony
(tools/quorum_loss.py + p2p/inproc.py link profiles):

* net.quorum_loss — a seeded >1/3 isolation window over a live
  4-validator fleet: height halts, zero conflicting commits, zero
  equivocations, the watchdog reports halt_reason="quorum_lost" from
  the blocking stage's vote bitmap, heal recovers to hash-identical
  commits within the bound; run twice to pin the same-seed outcome
  fingerprint
* net.asym        — the seeded ``asym`` profile (one lossy direction per
  pair, the reverse clean): the fleet keeps committing through the
  asymmetry and reconverges hash-identical once cleared
* net.gray        — ``gray`` links (60% loss, traffic still leaks) on
  every link touching one node: quorum keeps committing, the gray node
  is never declared dead and catches up hash-identical after the clear

Execution cells — the parallel-execution plane (state/parallel.py):

* exec.conflict_storm — every tx of every block writes the SAME key while
  the ``exec.conflict`` site scrambles conflict-lane assignments: the
  worst case for optimistic execution (everything conflicts, speculation
  buys nothing, validation + serial re-execution must carry the whole
  block). Commits must stay byte-identical to the serial spec — responses,
  app hash, results hash — across 3 heights

Crash cells — process death as the fault (tools/crashmatrix.py plane):

* crash.torn_wal — seeded torn WAL appends (``wal.torn_write``): replay
  stops at the tear, repair-on-open truncates the undecodable tail, and
  records appended AFTER the repair are never stranded behind garbage
* crash.privval  — a torn last-sign-state write (``privval.torn_state``):
  FilePV.load refuses to start with an actionable error naming the file
  (never a silent height-0 reset — that is the double-sign hazard)
* crash.loop     — the restart supervisor against an instant crasher:
  bounded exponential backoff walks its schedule, give-up fires after
  max_restarts consecutive fast crashes, and the crash-loop debugdump
  bundle records the full exit history

    python tools/chaos_matrix.py                     # full matrix
    python tools/chaos_matrix.py --quick             # skip the net cells
    python tools/chaos_matrix.py --sites statesync.lying_chunk --seeds 1,2
    python tools/chaos_matrix.py --self-test         # CI guard, seconds

Stdlib-only at the top level (argparse/subprocess/time): repo imports
happen inside cells so --help and --self-test's plumbing checks work
anywhere; the cells themselves need the repo on PYTHONPATH (the tool adds
it).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/chaos_matrix.py` puts tools/ first
    sys.path.insert(0, REPO)

DEFAULT_SEEDS = (1, 2, 3)
#: cell name -> slow?
SITES = {
    "device.batch_verify": False,
    "device.lane": False,
    "device.vote_flush": False,
    "wal.fsync": False,
    "db.write_batch": False,
    "net.drop": True,
    "ingest.mempool_full": True,
    "ingest.backpressure": True,
    # adversarial cells (content corruption / Byzantine peers)
    "net.corrupt": True,
    "statesync.lying_chunk": False,
    "statesync.lying_snapshot": False,
    "blocksync.bad_block": True,
    "lightserve.lying_server": False,
    "combo.maverick_corrupt": True,
    # churn cells (membership change as the fault; tools/churn.py rig)
    "churn.flap": True,
    "churn.rotate": True,
    "churn.partition32": True,
    "churn.corrupt32": True,
    # degraded-network cells (quorum loss + link profiles;
    # tools/quorum_loss.py + p2p/inproc.py LINK_PROFILES)
    "net.quorum_loss": True,
    "net.asym": True,
    "net.gray": True,
    # execution cells (the parallel-execution plane; state/parallel.py)
    "exec.conflict_storm": False,
    # aggregate-signature cells (the BLS commit plane; crypto/bls12381)
    "aggsig.degrade": False,
    # crash cells (process death as the fault; tools/crashmatrix.py plane)
    "crash.torn_wal": False,
    "crash.privval": False,
    "crash.loop": False,
    # game-day cell (the SLO soak plane; tools/soak.py + libs/slo.py)
    "soak.gameday": False,
}


def _pin_cpu_jax() -> None:
    """Mirror tests/conftest.py: pin jax to 8 virtual CPU devices and arm
    the repo's persistent compilation cache — the ed25519 verify kernel
    takes minutes to compile on CPU, and every cell is a fresh process."""
    if os.environ.get("TM_ON_DEVICE") == "1":
        return
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)


# -- cells (each runs in its own subprocess via --cell) ----------------------

def _signed(n, seed):
    from tendermint_tpu.crypto import Ed25519PrivKey

    out = []
    for i in range(n):
        sk = Ed25519PrivKey.generate(bytes([seed & 0xFF]) * 31 + bytes([i]))
        msg = b"chaos-%d-%d" % (seed, i)
        out.append((sk.pub_key(), msg, sk.sign(msg)))
    return out


def cell_device_batch_verify(seed: int) -> None:
    import numpy as np

    from tendermint_tpu.crypto.batch import BatchVerifier
    from tendermint_tpu.crypto.breaker import CLOSED, device_breaker
    from tendermint_tpu.libs.faults import faults

    device_breaker.failure_threshold = 2
    device_breaker.cooldown_s = 0.05
    faults.configure("device.batch_verify@0.6", seed=seed)
    cases = _signed(6, seed)
    for round_ in range(12):
        bv = BatchVerifier(backend="jax", plane="votes")
        bad = round_ % len(cases)
        for i, (pub, msg, sig) in enumerate(cases):
            bv.add(pub, msg, sig if i != bad
                   else sig[:-1] + bytes([sig[-1] ^ 1]))
        ok, per = bv.verify()
        expect = np.ones(len(cases), dtype=bool)
        expect[bad] = False
        assert not ok and (per == expect).all(), \
            f"round {round_}: verdicts diverged under injection: {per}"
        time.sleep(0.01)  # lets an OPEN breaker reach its half-open probe
    assert faults.fires("device.batch_verify") > 0, "site never fired"
    faults.reset()
    time.sleep(0.06)
    bv = BatchVerifier(backend="jax", plane="votes")
    for pub, msg, sig in cases:
        bv.add(pub, msg, sig)
    ok, _ = bv.verify()  # half-open probe (or already-closed device route)
    assert ok
    assert device_breaker.state == CLOSED, device_breaker.state


def cell_device_lane(seed: int) -> None:
    """One sick chip in the multi-device pool: the per-lane fault site
    (``device.lane.<label>``) is armed against EXACTLY ONE device label,
    its breaker opens, the pool degrades to the healthy peers with
    byte-identical verdicts and zero dropped signatures, and a healed lane
    rejoins. Shape-identical stub kernels (tools/device_profile) keep this
    off the multi-minute per-ordinal CPU compiles of the real kernel."""
    import os

    import numpy as np

    os.environ["TMTPU_DEVICE_BREAKER_THRESHOLD"] = "2"
    os.environ["TMTPU_DEVICE_BREAKER_COOLDOWN_S"] = "0.05"

    import device_profile as DP
    import jax

    from tendermint_tpu.crypto.breaker import (
        CLOSED,
        OPEN,
        lane_breaker,
        reset_lane_breakers,
    )
    from tendermint_tpu.crypto.ed25519_jax import multidevice as MD
    from tendermint_tpu.crypto.ed25519_jax import verify as V
    from tendermint_tpu.libs.faults import faults

    restore = DP.install_stub_kernels(V)
    try:
        rng = np.random.default_rng(seed)
        n = 1280
        pks = [rng.bytes(32) for _ in range(n)]
        msgs = [rng.bytes(120) for _ in range(n)]
        sigs = [rng.bytes(63) + b"\x00" for _ in range(n)]
        want = V._verify_segmented(pks, msgs, sigs, V.LANE)
        devs = jax.devices()[:4]
        sick = f"{devs[1].platform}:{devs[1].id}"
        faults.configure(f"device.lane.{sick}", seed=seed)  # always fires
        pool = MD.MultiDeviceStream(devices=devs, min_sigs=0)
        for round_ in range(4):
            got = pool.verify(pks, msgs, sigs, chunk=V.LANE)
            assert (got == want).all(), \
                f"round {round_}: verdicts diverged under lane injection"
        assert faults.fires(f"device.lane.{sick}") >= 2, "site never fired"
        assert lane_breaker(sick).state == OPEN, lane_breaker(sick).state
        assert pool.stats["resharded_segments"] >= 1
        # heal: disarm + clear breakers — the lane rejoins and verdicts
        # stay identical
        faults.reset()
        reset_lane_breakers()
        pool2 = MD.MultiDeviceStream(devices=devs, min_sigs=0)
        got = pool2.verify(pks, msgs, sigs, chunk=V.LANE)
        assert (got == want).all()
        assert lane_breaker(sick).state == CLOSED
        pool.shutdown()
        pool2.shutdown()
    finally:
        restore()


def cell_device_vote_flush(seed: int) -> None:
    import asyncio

    from tendermint_tpu.crypto.vote_batcher import BatchVoteVerifier
    from tendermint_tpu.libs.faults import faults

    faults.configure("device.vote_flush@0.5", seed=seed)
    verifier = BatchVoteVerifier(min_device_batch=2, deadline_s=0.005,
                                 device_timeout_s=600.0)

    async def run():
        for round_ in range(8):
            cases = _signed(4, seed * 100 + round_)
            bad = round_ % len(cases)
            results = await asyncio.gather(*(
                verifier.preverify(pub, msg, sig if i != bad
                                   else sig[:-1] + bytes([sig[-1] ^ 1]))
                for i, (pub, msg, sig) in enumerate(cases)))
            expect = [i != bad for i in range(len(cases))]
            assert results == expect, \
                f"round {round_}: {results} != {expect}"

    asyncio.run(run())


def cell_wal_fsync(seed: int) -> None:
    import tempfile

    from tendermint_tpu.consensus.wal import WAL, FsyncError
    from tendermint_tpu.libs.faults import faults

    path = os.path.join(tempfile.mkdtemp(prefix="chaos-wal-"), "cs.wal")
    WAL.fsync_error_policy = "raise"  # in-process harness; nodes use exit
    wal = WAL(path)  # the constructor's boot-marker sync runs un-armed
    k = seed % 5
    faults.configure(f"wal.fsync*1+{k}", seed=seed)  # fail the (k+1)-th
    written = 0
    try:
        for h in range(1, 30):
            wal.write_end_height(h, 1_700_000_000_000_000_000 + h)
            written += 1
        raise AssertionError("fault never fired")
    except FsyncError:
        pass
    wal.close()
    faults.reset()
    replayed = [m.data["height"] for m in WAL(path).iter_messages()
                if m.type == "end_height"]
    # boot marker, then every appended record: the failed-fsync record was
    # appended+flushed BEFORE its fsync, so it replays too — the crash
    # loses durability guarantees, never framing or durable prefixes
    assert replayed == [0] + list(range(1, written + 2)), \
        f"replay mismatch after injected fsync failure: {replayed}"


def cell_db_write_batch(seed: int) -> None:
    from tendermint_tpu.libs.db import BufferedDB, MemDB
    from tendermint_tpu.libs.faults import faults

    base = MemDB()
    buf = BufferedDB(base)
    keys = [b"k%d-%d" % (seed, i) for i in range(20)]
    for k in keys:
        buf.set(k, b"v" + k)
    faults.configure("db.write_batch*1", seed=seed)
    try:
        buf.flush()
        raise AssertionError("injected flush fault never raised")
    except OSError:
        pass
    # handled-but-not-durable guard: the window is still staged and the
    # base untouched; a disarmed retry lands everything
    assert base.get(keys[0]) is None
    assert buf.get(keys[0]) == b"v" + keys[0]
    faults.reset()
    buf.flush()
    for k in keys:
        assert base.get(k) == b"v" + k, f"record lost across retry: {k}"


def cell_net_drop(seed: int) -> None:
    import asyncio

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.p2p import InProcNetwork

    async def run():
        nodes = make_net(4)
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2, timeout=60)
            net.set_loss(0.10, seed=seed)
            h0 = min(nd.cs.state.last_block_height for nd in nodes)
            await wait_all_height(nodes, h0 + 3, timeout=120)
            assert net.chaos_stats()["dropped"] > 0
        finally:
            for nd in nodes:
                await nd.stop()
        common = min(nd.cs.state.last_block_height for nd in nodes) - 1
        hashes = {nd.block_store.load_block_meta(common).header.hash()
                  for nd in nodes}
        assert len(hashes) == 1, "divergent block hashes under loss"

    asyncio.run(run())


def cell_ingest_mempool_full(seed: int) -> None:
    """Ingestion-plane overload: open-loop tx load (tools/loadtime.py
    schedule, fixed-rate grid) into ONE validator whose mempool is shrunk
    to 8 slots, while a second validator is partitioned clean away. The
    survival property: rejection counters fire with reason="full", the
    tx lifecycle ring/active map stay bounded under the firehose, and the
    3/4 honest majority keeps committing with identical hashes."""
    import asyncio

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import loadtime as LT
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.libs.metrics import MempoolMetrics, Registry
    from tendermint_tpu.libs.txlife import TxLifecycle
    from tendermint_tpu.mempool.clist_mempool import MempoolError
    from tendermint_tpu.p2p import InProcNetwork

    ring_cap, active_cap = 32, 64
    m = MempoolMetrics(Registry())
    tl = TxLifecycle(sample_rate=1.0, ring_capacity=ring_cap,
                     active_capacity=active_cap)
    tl.metrics = m

    async def run():
        nodes = make_net(4)
        victim = nodes[0].mempool
        victim._max_txs = 8  # 8 slots vs a 400 tx/s firehose: always full
        victim.metrics = m
        victim.txlife = tl
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        try:
            await wait_all_height(nodes, 2, timeout=60)
            # one node partitioned clean away: 3/4 voting power remains
            net.partition({"node0", "node1", "node2"}, {"node3"})
            honest = nodes[:3]
            h0 = min(nd.cs.state.last_block_height for nd in honest)
            loop = asyncio.get_running_loop()
            sched = LT.plan_schedule(400.0, 240, t0=loop.time() + 0.05)
            rejected = 0
            for i, target in enumerate(sched):
                now = loop.time()
                if target > now:
                    await asyncio.sleep(target - now)
                tx = b"ingest-%d-%d=" % (seed, i) + b"x" * 64
                try:
                    victim.check_tx(tx)
                except MempoolError:
                    rejected += 1
            assert rejected > 0, "mempool never filled under open-loop load"
            # honest majority commits +2 heights DURING/after the overload
            await wait_all_height(honest, h0 + 2, timeout=120)
        finally:
            for nd in nodes:
                await nd.stop()
        common = min(nd.cs.state.last_block_height for nd in nodes[:3]) - 1
        hashes = {nd.block_store.load_block_meta(common).header.hash()
                  for nd in nodes[:3]}
        assert len(hashes) == 1, "divergent hashes among honest nodes"

    asyncio.run(run())
    # rejection counters fired with the right taxonomy...
    assert m.failed_txs.value("full") > 0, "full-mempool counter never fired"
    # ...and the lifecycle plane stayed bounded under the firehose
    snap = tl.snapshot(10 ** 6)
    assert len(snap["records"]) <= ring_cap, len(snap["records"])
    assert snap["active"] <= active_cap, snap["active"]
    assert snap["sealed_total"] > 0
    # depth gauges were maintained on every mutation path: the final value
    # is the real (small) post-run depth, never a stale high-water mark
    assert m.size.value() <= 8, m.size.value()


def cell_ingest_backpressure(seed: int) -> None:
    """Admission-control overload: an open-loop firehose (400 tx/s on the
    loadtime fixed-rate grid) through the ASYNC ingest pipeline into a
    sharded-lane mempool whose intake queue holds 16 slots, while one of
    4 validators is partitioned away. Survival properties: reason-labeled
    sheds fire (queue-full) and come back as explicit rejections — never
    a stall —, the intake queue never exceeds its bound, admitted txs
    flow through the lanes into blocks, and the honest 3/4 keep
    committing identical hashes."""
    import asyncio

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import loadtime as LT
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.libs.metrics import MempoolMetrics, Registry
    from tendermint_tpu.mempool.ingest import IngestPipeline, ShardedMempool
    from tendermint_tpu.p2p import InProcNetwork

    queue_limit = 16
    m = MempoolMetrics(Registry())

    async def run():
        nodes = make_net(4)
        # node0 runs the production fast path: sharded lanes behind the
        # same surface, rewired everywhere its CList was
        sm = ShardedMempool(nodes[0].conns.mempool, lanes=4)
        sm.metrics = m
        nodes[0].mempool = sm
        nodes[0].block_exec.mempool = sm
        nodes[0].mp_reactor.mempool = sm
        sm.tx_available_callbacks.append(nodes[0].cs.notify_txs_available)
        # deadline-paced flushes (batch_max above the bound): a 400 tx/s
        # firehose fills 16 slots in 40 ms, well inside the 100 ms flush
        # cadence — the front door MUST shed, and only the front door
        pipe = IngestPipeline(sm, batch_max=256, batch_deadline_s=0.1,
                              queue_limit=queue_limit)
        pipe.metrics = m
        net = InProcNetwork()
        for nd in nodes:
            net.add_switch(nd.switch)
        for nd in nodes:
            await nd.start()
        await net.connect_all()
        max_depth = 0
        try:
            await wait_all_height(nodes, 2, timeout=60)
            net.partition({"node0", "node1", "node2"}, {"node3"})
            honest = nodes[:3]
            h0 = min(nd.cs.state.last_block_height for nd in honest)
            loop = asyncio.get_running_loop()
            sched = LT.plan_schedule(400.0, 240, t0=loop.time() + 0.05)
            accepted = 0
            for i, target in enumerate(sched):
                now = loop.time()
                if target > now:
                    await asyncio.sleep(target - now)
                tx = b"bp-%d-%d=" % (seed, i) + b"x" * 64
                if pipe.submit_nowait(tx):
                    accepted += 1
                max_depth = max(max_depth, pipe.queue_depth())
            await pipe.flush_now()
            assert accepted > 0, "pipeline admitted nothing"
            # overload DID shed, with the right reason, as explicit
            # (awaitable) rejections — the submit path never raises/stalls
            shed = await pipe.submit(b"bp-probe" + b"y" * 64) \
                if pipe.queue_depth() >= queue_limit else None
            assert pipe.stats["shed_queue-full"] > 0, dict(pipe.stats)
            if shed is not None:
                assert shed.code == 1 and "queue-full" in shed.log
            # honest majority commits +2 heights during/after the storm
            await wait_all_height(honest, h0 + 2, timeout=120)
        finally:
            await pipe.stop()
            for nd in nodes:
                await nd.stop()
        assert max_depth <= queue_limit, \
            f"intake queue exceeded its bound: {max_depth}"
        common = min(nd.cs.state.last_block_height for nd in nodes[:3]) - 1
        hashes = {nd.block_store.load_block_meta(common).header.hash()
                  for nd in nodes[:3]}
        assert len(hashes) == 1, "divergent hashes among honest nodes"

    asyncio.run(run())
    assert m.shed_txs_total.value("queue-full") > 0, \
        "queue-full shed counter never fired"
    # no other shed reason applies to this cell's knobs
    assert m.shed_txs_total.value("sender-rate") == 0
    assert m.shed_txs_total.value("fee-floor") == 0


async def _live_net_under(site_spec: str, seed: int, extra_heights: int = 3,
                          mavericks=None, post_wait=None):
    """Shared adversarial-net driver: 4 in-proc validators, the given fault
    spec armed mid-run, a persistent-peer-style reconnect loop (corrupted
    payloads make receivers drop links), +N heights, identical hashes.
    ``post_wait`` (async) runs while the net is still live — e.g. to wait
    for an injection cap to be reached."""
    import asyncio

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_consensus_net import make_net, wait_all_height

    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.p2p import InProcNetwork

    nodes = make_net(4)
    for idx, height_map in (mavericks or {}).items():
        nodes[idx].cs.misbehaviors = dict(height_map)
    net = InProcNetwork()
    for nd in nodes:
        net.add_switch(nd.switch)
    for nd in nodes:
        await nd.start()
    await net.connect_all()

    async def rewire():
        while True:
            await asyncio.sleep(0.3)
            await net.reconnect_missing()

    rewire_task = asyncio.create_task(rewire())
    try:
        await wait_all_height(nodes, 2, timeout=60)
        faults.configure(site_spec, seed=seed)
        h0 = min(nd.cs.state.last_block_height for nd in nodes)
        await wait_all_height(nodes, h0 + extra_heights, timeout=180)
        if post_wait is not None:
            await post_wait()
        # disarm BEFORE teardown so shutdown traffic doesn't tail-fire
        faults.reset()
    finally:
        rewire_task.cancel()
        for nd in nodes:
            await nd.stop()
    common = min(nd.cs.state.last_block_height for nd in nodes) - 1
    hashes = {nd.block_store.load_block_meta(common).header.hash()
              for nd in nodes}
    assert len(hashes) == 1, "divergent block hashes under corruption"


def cell_net_corrupt(seed: int) -> None:
    import asyncio

    from tendermint_tpu.libs.faults import faults

    cap = 30
    observed = []

    async def until_cap():
        # the armed net keeps committing (empty blocks) so traffic keeps
        # evaluating the site; the cap WILL be reached — wait for it so the
        # injection count is exactly reproducible across seeds/runs
        deadline = asyncio.get_running_loop().time() + 60
        while faults.fires("net.corrupt") < cap:
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.25)
        observed.append(faults.fires("net.corrupt"))

    asyncio.run(_live_net_under(f"net.corrupt@0.1*{cap}", seed,
                                post_wait=until_cap))
    assert observed and observed[0] == cap, \
        f"expected {cap} injections, saw {observed}"


def cell_combo_maverick_corrupt(seed: int) -> None:
    """The Byzantine combo: a double-prevoting validator AND corrupt links
    at once — honest nodes must keep committing and agree."""
    import asyncio

    from tendermint_tpu.libs.faults import faults

    observed = []

    async def snap_fires():
        observed.append(faults.fires("net.corrupt"))

    asyncio.run(_live_net_under("net.corrupt@0.1*10", seed,
                                extra_heights=4,
                                mavericks={3: {3: "double-prevote"}},
                                post_wait=snap_fires))
    assert observed and observed[0] > 0, "site never fired"


def _statesync_harness():
    """Server app with a multi-chunk snapshot + fresh client app + stub
    state provider — the in-proc Byzantine statesync rig."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.example.kvstore import SnapshotKVStoreApplication
    from tendermint_tpu.statesync.stateprovider import StateProvider

    server = SnapshotKVStoreApplication(interval=1)
    for i in range(40):
        server.deliver_tx(abci.RequestDeliverTx(
            tx=f"key{i:03d}={'v' * 150}".encode()))
    server.commit()  # height 1: snapshot with ~7 chunks
    client = SnapshotKVStoreApplication(interval=1)

    class StubProvider(StateProvider):
        async def app_hash(self, height):
            return server.app_hash

        async def commit(self, height):
            return "commit"

        async def state(self, height):
            return "state"

    return server, client, StubProvider()


def _run_lying_chunk_restore(seed: int):
    """One full restore against 2 honest peers + 1 always-lying chunk
    server; returns (syncer, injected fire count)."""
    import asyncio
    import random as _random

    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.libs.peerscore import PeerScoreboard
    from tendermint_tpu.statesync.msgs import ChunkResponse
    from tendermint_tpu.statesync.syncer import Syncer

    server, client, provider = _statesync_harness()
    faults.configure("statesync.lying_chunk", seed=seed)

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            resp = server.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(height, fmt, idx))
            chunk = resp.chunk
            if peer_id == "liar":  # the serving reactor's fault seam
                chunk = faults.mutate("statesync.lying_chunk", chunk)
            syncer.add_chunk(
                ChunkResponse(height, fmt, idx, chunk, not resp.chunk),
                peer_id)

        syncer = Syncer(client, client, provider, request_chunk,
                        chunk_timeout=2.0,
                        rng=_random.Random(seed),
                        scoreboard=PeerScoreboard(ban_threshold=2, seed=seed))
        snaps = server.list_snapshots(abci.RequestListSnapshots()).snapshots
        for s in snaps:
            for pid in ("honest-a", "honest-b", "liar"):
                syncer.add_snapshot(pid, s)
        state, commit = await syncer.sync_any(discovery_time=0.01)
        assert (state, commit) == ("state", "commit")
        return syncer

    syncer = asyncio.run(run())
    assert client.state == server.state, "restored state diverged"
    return syncer, faults.fires("statesync.lying_chunk")


def cell_statesync_lying_chunk(seed: int) -> None:
    from tendermint_tpu.libs.faults import faults

    syncer, fires1 = _run_lying_chunk_restore(seed)
    assert fires1 > 0, "liar was never asked for a chunk"
    assert syncer.scoreboard.banned("liar"), \
        f"liar not banned: {syncer.scoreboard.snapshot()}"
    assert not syncer.scoreboard.banned("honest-a")
    assert not syncer.scoreboard.banned("honest-b")
    # replayability: same seed, fresh plane -> identical injection count
    faults.reset()
    syncer2, fires2 = _run_lying_chunk_restore(seed)
    assert fires2 == fires1, f"injection count diverged: {fires1} != {fires2}"
    assert syncer2.scoreboard.banned("liar")


def cell_statesync_lying_snapshot(seed: int) -> None:
    import asyncio

    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.libs.peerscore import PeerScoreboard
    from tendermint_tpu.statesync.msgs import ChunkResponse
    from tendermint_tpu.statesync.syncer import Syncer

    server, client, provider = _statesync_harness()
    faults.configure("statesync.lying_snapshot*1", seed=seed)

    async def run():
        async def request_chunk(peer_id, height, fmt, idx):
            resp = server.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(height, fmt, idx))
            syncer.add_chunk(
                ChunkResponse(height, fmt, idx, resp.chunk, not resp.chunk),
                peer_id)

        syncer = Syncer(client, client, provider, request_chunk,
                        chunk_timeout=2.0,
                        scoreboard=PeerScoreboard(ban_threshold=1, seed=seed))
        snaps = server.list_snapshots(abci.RequestListSnapshots()).snapshots

        def rediscover():
            # honest advertisers answer the re-ask after the lie collapses
            for s in snaps:
                for pid in ("honest-a", "honest-b"):
                    syncer.add_snapshot(pid, s)

        # initially only the liar has been heard from — with a bogus hash
        # (the serving reactor's statesync.lying_snapshot seam); tampered
        # COPIES so the honest re-advertisements above stay honest
        for s in snaps:
            syncer.add_snapshot("liar", abci.Snapshot(
                s.height, s.format, s.chunks,
                faults.mutate("statesync.lying_snapshot", s.hash),
                s.metadata))
        state, commit = await syncer.sync_any(discovery_time=0.05,
                                              rediscover=rediscover)
        assert (state, commit) == ("state", "commit")
        return syncer

    syncer = asyncio.run(run())
    assert client.state == server.state
    assert syncer.scoreboard.banned("liar"), \
        f"lying advertiser not banned: {syncer.scoreboard.snapshot()}"
    assert faults.fires("statesync.lying_snapshot") == 1


def cell_blocksync_bad_block(seed: int) -> None:
    """A fresh node fast-syncs although providers serve a capped number of
    tampered block responses: redo + scoreboard strikes, never a wedge."""
    import asyncio

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_block_sync import SyncNode, build_chain
    from tendermint_tpu import crypto
    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.p2p import InProcNetwork
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x42" * 32))
    genesis = GenesisDoc(
        chain_id="sync-chain", genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.get_pub_key(), 10)])

    async def run():
        from dataclasses import replace

        from tendermint_tpu.consensus.config import test_consensus_config

        quiet = replace(test_consensus_config(), create_empty_blocks=False)
        chain = build_chain(40, pv, genesis)
        src_a = SyncNode("src_a", genesis, pv=pv, fast_sync=False,
                         chain=chain, config=quiet)
        src_b = SyncNode("src_b", genesis, pv=None, fast_sync=True,
                         config=quiet)
        fresh = SyncNode("fresh", genesis, pv=None, fast_sync=True,
                         config=quiet)
        net = InProcNetwork()
        for nd in (src_a, src_b, fresh):
            net.add_switch(nd.switch)
        await src_a.start()
        await src_b.start()
        await net.connect("src_a", "src_b")
        # second source catches up honestly first, then serves too
        await asyncio.wait_for(src_b.bc_reactor.synced.wait(), timeout=120)
        # arm AFTER the honest warm-up: the very next served block response
        # is tampered (*1 => exactly one injection, every seed, every run)
        faults.configure("blocksync.bad_block*1", seed=seed)

        async def rewire():
            # a corrupted response that fails decode drops the link; the
            # in-proc analog of persistent-peer redial keeps serving alive
            while True:
                await asyncio.sleep(0.3)
                await net.reconnect_missing()

        rewire_task = asyncio.create_task(rewire())
        await fresh.start()
        await net.connect("src_a", "fresh")
        await net.connect("src_b", "fresh")
        try:
            await asyncio.wait_for(fresh.bc_reactor.synced.wait(), timeout=120)
            assert fresh.state_store.load().last_block_height >= 39
        finally:
            rewire_task.cancel()
            for nd in (fresh, src_b, src_a):
                await nd.stop()
        return fresh

    fresh = asyncio.run(run())
    fires = faults.fires("blocksync.bad_block")
    assert fires == 1, f"expected exactly 1 injection, saw {fires}"
    strikes = sum(s["total_failures"]
                  for s in fresh.bc_reactor.scoreboard.snapshot().values())
    assert strikes > 0, "victim never struck a lying provider"


def cell_lightserve_lying_server(seed: int) -> None:
    """A serving node armed with ``lightserve.lying_server`` swaps served
    headers for a re-signed equivocation fork (same keys, different
    app_hash — it VERIFIES); a bisecting light-client fleet sharing one
    scoreboard catches the lie by witness cross-check, strikes the liar
    severely (instant ban), and honest serving continues for the rest of
    the fleet. Replay: same seed => identical injection count."""
    import asyncio
    import copy

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_light_client import CHAIN, T0, _keys, _mk_chain, _resign
    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.libs.peerscore import PeerScoreboard
    from tendermint_tpu.light import LightClient, TrustOptions
    from tendermint_tpu.light.client import DivergenceError
    from tendermint_tpu.light.serve import TAMPER_SITE, ServeProvider

    os.environ.setdefault("TMTPU_BATCH_BACKEND", "host")
    # validator rotation at height 5 forces the fleet to bisect: many
    # heights served, many chances for the armed site to lie
    a, b = _keys(0x50, 4), _keys(0x60, 4)
    key_sets = [a, a, a, a, b, b, b, b, b, b]
    honest = _mk_chain(key_sets, 10)
    forged = copy.deepcopy(honest)
    for h in forged:
        forged[h].signed_header.header.app_hash = b"\xee" * 32
    # _resign needs one key list per height: rebuild per rotated set
    lo = _resign({h: forged[h] for h in range(1, 5)}, a)
    hi = _resign({h: forged[h] for h in range(5, 11)}, b)
    forged = {**lo, **hi}
    now = T0 + 100 * 1_000_000_000

    def run_fleet():
        primary = ServeProvider(CHAIN, honest, name="primary")
        liar = ServeProvider(CHAIN, honest,
                             forged={h: forged[h] for h in range(2, 11)},
                             name="liar")
        witnesses = [liar, ServeProvider(CHAIN, honest, name="honest-a"),
                     ServeProvider(CHAIN, honest, name="honest-b")]
        sb = PeerScoreboard(name="light", seed=seed)
        trust = TrustOptions(3600.0, 1,
                             honest[1].signed_header.header.hash())

        async def run():
            caught = 0
            for _ in range(3):  # the fleet: one scoreboard, fresh clients
                client = LightClient(CHAIN, trust, primary, witnesses,
                                     scoreboard=sb)
                try:
                    lb = await client.verify_light_block_at_height(
                        10, now_ns=now)
                    assert lb.signed_header.header.height == 10
                except DivergenceError as e:
                    assert e.witness_id == "liar", e
                    caught += 1
            return caught

        caught = asyncio.run(run())
        return caught, sb, liar

    faults.configure(f"{TAMPER_SITE}@0.75", seed=seed)
    caught1, sb, liar = run_fleet()
    fires1 = faults.fires(TAMPER_SITE)
    assert fires1 > 0, "lying site never fired"
    assert caught1 >= 1, "no client ever caught the liar"
    assert sb.banned("liar"), f"liar not banned: {sb.snapshot()}"
    assert not sb.banned("honest-a") and not sb.banned("honest-b")
    assert liar.evidence, "divergence evidence never reported"
    # honest serving continued: with the liar banned (skipped on
    # cross-check) at least one later client completed the bisection
    assert caught1 < 3, "serving never recovered after the ban"
    # replayability: same seed, fresh plane -> identical injection count
    faults.reset()
    faults.configure(f"{TAMPER_SITE}@0.75", seed=seed)
    caught2, sb2, _ = run_fleet()
    fires2 = faults.fires(TAMPER_SITE)
    assert (fires2, caught2) == (fires1, caught1), \
        f"replay diverged: {(fires1, caught1)} != {(fires2, caught2)}"
    assert sb2.banned("liar")
    faults.reset()


def _churn_mod():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import churn

    return churn


def cell_churn_flap(seed: int) -> None:
    """A flapping node: 3 leave/rejoin cycles, every rejoin a full
    statesync restore; survivors never redial the departed id, hashes
    identical (all asserted inside run_flap)."""
    churn = _churn_mod()

    report = churn.run_flap(cycles=3, seed=seed)
    assert len(report["rejoin_caughtup_s"]) == 3, report
    assert all(s < 60 for s in report["rejoin_caughtup_s"]), report


def cell_churn_rotate(seed: int) -> None:
    """The full N=8 churn schedule: joins + leaves + validator rotation
    across prune boundaries under open-loop load. run_churn asserts
    liveness, survivor app-hash agreement, prune-floor resolution, and
    bounded book/scoreboard state; the cell checks the schedule shape."""
    churn = _churn_mod()

    report = churn.run_churn(n_nodes=8, intervals=2, seed=seed)
    assert report["rotations"] == 2, report
    assert len(report["join_caughtup_s"]) == 2, report
    actions = [a for a, _ in report["executed"]]
    assert actions.count("leave") == 2 and actions.count("join") == 2


def _net32(seed: int, drive):
    """Shared 32-node sparse-fleet driver: build, run `drive(net, nodes)`,
    assert all 32 agree on a common block hash, tear down."""
    import asyncio

    churn = _churn_mod()

    async def run():
        net, nodes, _pvs, _genesis = await churn.build_fleet(
            32, topology="sparse", degree=4, seed=seed)
        try:
            await churn._wait_heights(list(nodes.values()), 3, timeout=240)
            await drive(net, nodes, churn)
        finally:
            for nd in nodes.values():
                try:
                    await nd.stop()
                except Exception:
                    pass
        common = min(nd.height for nd in nodes.values()) - 1
        hashes = {nd.block_store.load_block_meta(common).header.app_hash
                  for nd in nodes.values()}
        assert len(hashes) == 1, "divergent hashes across the 32-node net"

    asyncio.run(run())


def cell_churn_partition32(seed: int) -> None:
    """Partition at scale: 8 of 32 sparse-topology nodes blackholed; the
    majority keeps committing, heal reconverges everyone."""
    async def drive(net, nodes, churn):
        minority = {f"full{i}" for i in range(20, 28)}
        net.partition(set(nodes) - minority, minority)
        majority = [nd for n, nd in nodes.items() if n not in minority]
        h0 = max(nd.height for nd in majority)
        await churn._wait_heights(majority, h0 + 2, timeout=180)
        net.heal()
        h1 = max(nd.height for nd in majority)
        await churn._wait_heights(list(nodes.values()), h1 + 1, timeout=240)

    _net32(seed, drive)


def cell_churn_corrupt32(seed: int) -> None:
    """Content corruption at scale: capped bit flips on the 32-node sparse
    net's in-flight payloads; receivers drop corrupting links, the redial
    loop re-heals, commits continue."""
    import asyncio

    from tendermint_tpu.libs.faults import faults

    cap = 20

    async def drive(net, nodes, churn):
        rewire_task = asyncio.create_task(churn.rewire_loop(net))
        try:
            faults.configure(f"net.corrupt@0.02*{cap}", seed=seed)
            h0 = max(nd.height for nd in nodes.values())
            await churn._wait_heights(list(nodes.values()), h0 + 3,
                                      timeout=300)
            assert faults.fires("net.corrupt") > 0, "site never fired"
        finally:
            # disarm on EVERY exit — 32 nodes tearing down under live bit
            # flips would bury the real failure in link-drop noise
            faults.reset()
            rewire_task.cancel()

    _net32(seed, drive)


def cell_crash_torn_wal(seed: int) -> None:
    """Torn WAL tail, repaired on open: arm the byte-emit tear site so the
    LAST append lands partial, prove replay stops at the tear, and prove a
    reopen truncates the garbage so new appends are replayable (the
    stranded-records regression the repair exists for)."""
    import tempfile

    from tendermint_tpu.consensus.wal import WAL
    from tendermint_tpu.libs.faults import faults

    path = os.path.join(tempfile.mkdtemp(prefix="chaos-torn-"), "cs.wal")
    wal = WAL(path)
    for h in range(1, 6):
        wal.write_end_height(h, 1_700_000_000_000_000_000 + h)
    # tear exactly the NEXT append (the tail record a crash would tear)
    faults.configure("wal.torn_write*1", seed=seed)
    wal.write_end_height(6, 1_700_000_000_000_000_006)
    assert faults.fires("wal.torn_write") == 1, "tear site never fired"
    faults.reset()
    wal.close()
    # replay stops cleanly at (or before) the torn record
    replayed = [m.data["height"] for m in WAL(path, repair=False)
                .iter_messages() if m.type == "end_height"]
    assert replayed[:6] == [0, 1, 2, 3, 4, 5], replayed
    assert 6 not in replayed, "a torn record must never replay whole"
    # repair-on-open: append after the tear, the new record must replay
    wal2 = WAL(path)
    size_after_repair = os.path.getsize(path)
    assert WAL._decodable_prefix_len(
        open(path, "rb").read()) == size_after_repair, \
        "repair left undecodable bytes in the head"
    wal2.write_end_height(7, 1_700_000_000_000_000_007)
    wal2.close()
    replayed = [m.data["height"] for m in WAL(path).iter_messages()
                if m.type == "end_height"]
    assert replayed[-1] == 7, \
        f"record appended after repair was stranded: {replayed}"
    # determinism: the same seed tears the same bytes
    fp1 = faults.configure("wal.torn_write*1", seed=seed).tear(
        "wal.torn_write", b"A" * 64)
    faults.reset()
    fp2 = faults.configure("wal.torn_write*1", seed=seed).tear(
        "wal.torn_write", b"A" * 64)
    faults.reset()
    assert fp1 == fp2, "tear schedule not deterministic per seed"


def cell_crash_privval(seed: int) -> None:
    """Torn last-sign-state: the atomic write emits a partial file, and
    the next startup REFUSES with an error naming the file — never a
    silent height-0 reset (the double-sign hazard)."""
    import tempfile

    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.privval.file_pv import CorruptSignStateError, FilePV
    from tendermint_tpu.types import (BlockID, PartSetHeader, SignedMsgType,
                                      Vote)

    d = tempfile.mkdtemp(prefix="chaos-pv-")
    key, state = os.path.join(d, "pv_key.json"), os.path.join(d, "pv_state.json")
    pv = FilePV.generate(key, state, seed=bytes([seed & 0xFF]) * 32)
    pv.save()
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))

    def vote(h):
        return Vote(SignedMsgType.PREVOTE, h, 0, bid,
                    1_700_000_000_000_000_000, b"\xaa" * 20, 0)

    pv.sign_vote("chaos-chain", vote(1))          # clean sign + save
    faults.configure("privval.torn_state*1", seed=seed)
    pv.sign_vote("chaos-chain", vote(2))          # state write torn
    assert faults.fires("privval.torn_state") == 1, "tear site never fired"
    faults.reset()
    try:
        FilePV.load(key, state)
        raise AssertionError("corrupt sign state silently accepted")
    except CorruptSignStateError as e:
        assert state in str(e), f"error does not name the file: {e}"
        assert "double-sign" in str(e), e
    # after the operator restores the file, startup works again
    pv.last_sign_state.save()                     # un-torn rewrite
    pv2 = FilePV.load(key, state)
    assert pv2.last_sign_state.height == 2


def cell_crash_loop(seed: int) -> None:
    """Crash-loop give-up: an instant crasher walks the bounded backoff
    schedule, exhausts max_restarts, and the supervisor gives up with a
    debugdump bundle holding the exit history."""
    import json
    import tempfile

    from tendermint_tpu.libs.supervisor import (RestartPolicy,
                                                RestartSupervisor,
                                                write_crashloop_bundle)

    clock = [0.0]
    policy = RestartPolicy(policy="on-failure", max_restarts=3,
                           backoff_s=0.5, backoff_max_s=4.0,
                           healthy_uptime_s=10.0)
    sup = RestartSupervisor(policy, name=f"crasher{seed}",
                            time_fn=lambda: clock[0])
    delays = []
    for _ in range(10):
        sup.on_launch()
        clock[0] += 0.01            # dies instantly every time
        delay = sup.on_exit(1)
        if delay is None:
            break
        delays.append(delay)
    assert sup.gave_up, "supervisor never gave up on an instant crasher"
    assert delays == [0.5, 1.0, 2.0], delays   # bounded doubling
    assert sup.restarts == policy.max_restarts
    # a healthy run re-earns the budget (not a crash loop)
    sup2 = RestartSupervisor(policy, name="occasional",
                             time_fn=lambda: clock[0])
    for _ in range(6):
        sup2.on_launch()
        clock[0] += 60.0            # an hour of uptime per life
        assert sup2.on_exit(1) == 0.5
    assert not sup2.gave_up
    # the give-up artifact records the whole history
    out = tempfile.mkdtemp(prefix="chaos-loop-")
    bundle = write_crashloop_bundle(out, sup, extras={"seed": str(seed)})
    with open(bundle) as f:
        doc = json.load(f)
    assert doc["crashloop"]["gave_up"] is True
    assert len(doc["crashloop"]["history"]) == policy.max_restarts + 1
    assert doc["crashloop"]["history"][-1]["action"] == "give-up"


def cell_exec_conflict_storm(seed: int) -> None:
    """All-same-key blocks under parallel execution with the
    exec.conflict chaos site scrambling lane assignments: the serial and
    parallel executors must commit byte-identical results at every
    height."""
    from tendermint_tpu import crypto
    from tendermint_tpu.abci.example.kvstore import MerkleKVStoreApplication
    from tendermint_tpu.config import ExecutionConfig
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.libs.faults import faults
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.state import (BlockExecutor, StateStore,
                                      state_from_genesis)
    from tendermint_tpu.state.execution import (EmptyEvidencePool,
                                                NoOpMempool)
    from tendermint_tpu.store import BlockStore
    from tendermint_tpu.types import (BlockID, GenesisDoc, GenesisValidator,
                                      MockPV, SignedMsgType, Vote, VoteSet)
    from tendermint_tpu.types.block import Commit

    import random

    def run(version, arm):
        if arm:
            faults.configure("exec.conflict", seed=seed)
        try:
            pv = MockPV(crypto.Ed25519PrivKey.generate(b"\x21" * 32))
            genesis = GenesisDoc(
                chain_id=f"storm-{seed}",
                genesis_time_ns=1_700_000_000_000_000_000,
                validators=[GenesisValidator(pv.get_pub_key(), 10)])
            state = state_from_genesis(genesis)
            app = MerkleKVStoreApplication()
            conns = AppConns(local_client_creator(app))
            conns.start()
            ss = StateStore(MemDB())
            ss.save(state)
            ex = BlockExecutor(ss, conns.consensus, NoOpMempool(),
                               EmptyEvidencePool(), BlockStore(MemDB()),
                               exec_config=ExecutionConfig(version=version))
            wl_rng = random.Random(seed)  # identical workload both runs
            last_commit = Commit(0, 0, BlockID(), [])
            out = []
            for h in range(1, 4):
                txs = [b"storm=%d.%d.%08x" % (h, i, wl_rng.getrandbits(32))
                       for i in range(30)]
                proposer = state.validators.get_proposer().address
                block, parts = state.make_block(h, txs, last_commit, [],
                                                proposer)
                bid = BlockID(block.hash(), parts.header())
                state, _ = ex.apply_block(state, bid, block)
                vs = VoteSet(state.chain_id, h, 0, SignedMsgType.PRECOMMIT,
                             state.validators)
                v = Vote(SignedMsgType.PRECOMMIT, h, 0, bid,
                         block.header.time_ns + 1,
                         state.validators.validators[0].address, 0)
                pv.sign_vote(state.chain_id, v)
                vs.add_vote(v)
                last_commit = vs.make_commit()
                out.append((ss.load_abci_responses(h).to_json(),
                            state.app_hash, state.last_results_hash))
            # storm property: the whole block is ONE conflict group (or,
            # with the chaos site scrambling, re-executed serially)
            if version == "v1":
                assert ex._parallel.last_groups >= 1
            return out, dict(app.state), app.tx_count
        finally:
            if arm:
                faults.reset()

    serial = run("v0", arm=False)
    parallel = run("v1", arm=True)
    assert serial == parallel, "conflict storm diverged from serial spec"


def cell_aggsig_degrade(seed: int) -> None:
    """BLS aggregate-verify under device strikes: the armed
    ``crypto.bls_verify`` site fails EVERY jax apk aggregation, the device
    breaker opens, and every single verify still returns the host-scalar
    verdict — zero dropped commits, accept AND reject parity throughout
    the degradation. After disarm + cooldown, a single-key aggregate (the
    n==1 device-evidence probe in aggregate_pubkeys_vec) re-closes the
    breaker."""
    from tendermint_tpu.crypto import bls12381 as bls
    from tendermint_tpu.crypto.bls12381 import vec
    from tendermint_tpu.crypto.breaker import CLOSED, OPEN, device_breaker
    from tendermint_tpu.libs.faults import faults

    device_breaker.failure_threshold = 2
    # long cooldown while armed: the scalar-fallback pairing (~100 ms)
    # must not outlast the OPEN window, or every call would be a fresh
    # half-open probe and no breaker rejection would ever be observed
    device_breaker.cooldown_s = 30.0
    vec.reset_stats()
    bls.reset()

    sks = [bls.sk_from_seed(bytes([seed & 0xFF, i])) for i in range(4)]
    pks = [bls.sk_to_pk(sk) for sk in sks]
    msg = b"aggsig-degrade-%d" % seed
    good = bls.aggregate([bls.sign(sk, msg) for sk in sks])
    bad = bytes([good[0] ^ 0x01]) + good[1:]

    faults.configure("crypto.bls_verify@1.0", seed=seed)
    try:
        for round_ in range(6):
            # every call lands a verdict (fallback, never a drop), and the
            # verdict matches the scalar spec for valid AND tampered input
            assert vec.fast_aggregate_verify_routed(pks, msg, good,
                                                    backend="jax"), \
                f"round {round_}: valid aggregate rejected under injection"
            assert not vec.fast_aggregate_verify_routed(pks, msg, bad,
                                                        backend="jax"), \
                f"round {round_}: tampered aggregate accepted under injection"
        assert faults.fires("crypto.bls_verify") > 0, "site never fired"
        assert vec.stats["device_errors"] >= 2, vec.stats
        assert vec.stats["breaker_rejections"] > 0, \
            "breaker never opened under 100% strikes"
        assert device_breaker.state == OPEN, device_breaker.state
    finally:
        faults.reset()
    device_breaker.cooldown_s = 0.05
    time.sleep(0.06)
    # half-open probe with REAL device evidence: the single-key aggregate
    # runs the Montgomery limb roundtrip on the jax backend
    assert vec.fast_aggregate_verify_routed(
        [pks[0]], pks[0], bls.pop_prove(sks[0]), dst=bls.DST_POP,
        backend="jax")
    assert device_breaker.state == CLOSED, device_breaker.state
    assert vec.stats["device_calls"] >= 1, vec.stats


def _soak_mod():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import soak

    return soak


def cell_soak_gameday(seed: int) -> None:
    """A compressed game day through the SLO soak plane: the chaos
    schedule must be a pure function of the seed, the live fleet must
    make height progress under the armed corrupt+churn windows, and
    every SLO breach the engine raises must leave with an attribution —
    a named plane or the loud ``unattributed``, never silence."""
    import tempfile

    soak = _soak_mod()

    plan_a = soak.plan_gameday(seed, n_nodes=5, duration_s=22.0)
    plan_b = soak.plan_gameday(seed, n_nodes=5, duration_s=22.0)
    assert plan_a == plan_b, "gameday plan is not seed-deterministic"
    assert soak.schedule_fingerprint(plan_a) == \
        soak.schedule_fingerprint(plan_b)
    planes = [ev["plane"] for ev in plan_a["events"]]
    # 5 nodes: one spare full (churn) + the always-on corrupt plane +
    # the quorum-loss window a full 4-validator quorum always gets
    assert planes == ["churn", "corrupt", "quorum_loss"], planes

    out = os.path.join(tempfile.mkdtemp(prefix="chaos_soak_"),
                       "soak_report.json")
    rep = soak.run_soak(n_nodes=5, seed=seed, duration_s=22.0, out=out)
    assert rep["schedule_fingerprint"] == soak.schedule_fingerprint(plan_a), \
        "live run drifted from the pure plan"
    assert rep["heights"]["final"] > rep["heights"]["initial"], rep["heights"]
    assert sorted(p for p, _ in rep["executed"]) == sorted(planes), \
        rep["executed"]
    assert not rep["event_errors"], rep["event_errors"]
    for b in rep["slo"]["breaches"]:
        att = b.get("attribution")
        assert att and att.get("plane"), f"silent breach: {b}"
    assert os.path.exists(out), "report never written"


def _quorum_loss_mod():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import quorum_loss

    return quorum_loss


def cell_net_quorum_loss(seed: int) -> None:
    """The partially-synchronous contract under >1/3 isolation: a seeded
    quorum-loss window over a live 4-validator fleet halts height advance
    with zero conflicting commits and zero equivocations, the survivor's
    watchdog classifies the halt ``quorum_lost`` from the blocking
    stage's vote bitmap, and post-heal the fleet recovers to
    hash-identical commits — run TWICE to pin the same-seed outcome
    fingerprint (all asserted inside run_quorum_loss)."""
    ql = _quorum_loss_mod()

    assert ql.plan_quorum_loss(seed, 1) == ql.plan_quorum_loss(seed, 1)
    vd = ql.verify_determinism(seed=seed, windows=1)
    assert vd["ok"], f"same-seed outcomes diverged: {vd}"
    assert all(s < ql.RECOVER_BOUND_S for s in vd["recover_s"]), vd


def cell_net_asym(seed: int) -> None:
    """Asymmetric degradation: the seeded ``asym`` profile makes one
    direction of every pair lossy while the reverse stays clean — the
    regime TCP-ish failure detectors misread. The 5-node fleet must keep
    committing through it and reconverge hash-identical once cleared."""
    import asyncio

    from tendermint_tpu.p2p.inproc import plan_link_profiles

    churn = _churn_mod()

    ids = [f"n{i}" for i in range(5)]
    plan = plan_link_profiles(ids, "asym", seed=seed)
    assert plan == plan_link_profiles(ids, "asym", seed=seed)
    # one degraded direction per pair, never both
    for (src, dst) in plan:
        assert (dst, src) not in plan, f"both directions degraded: {src},{dst}"

    async def run():
        net, nodes, _pvs, _genesis = await churn.build_fleet(5, seed=seed)
        try:
            for nd in nodes.values():
                nd.cs.config.gossip_stall_refresh_s = 1.0
            applied = net.apply_profile("asym", seed=seed)
            assert applied == len(net.links) // 2, applied
            await churn._wait_heights(list(nodes.values()), 2, timeout=120)
            h0 = max(nd.height for nd in nodes.values())
            await churn._wait_heights(list(nodes.values()), h0 + 3,
                                      timeout=300)
            net.clear_policies()
            h1 = max(nd.height for nd in nodes.values())
            await churn._wait_heights(list(nodes.values()), h1 + 1,
                                      timeout=120)
            common = min(nd.height for nd in nodes.values()) - 1
            hashes = {nd.block_store.load_block_meta(common).header.app_hash
                      for nd in nodes.values()}
            assert len(hashes) == 1, "hashes diverged under asym links"
        finally:
            for nd in nodes.values():
                try:
                    await nd.stop()
                except Exception:
                    pass

    asyncio.run(run())


def cell_net_gray(seed: int) -> None:
    """Gray failure: every link touching one full node runs the ``gray``
    profile (60% loss — traffic leaks, so nothing declares the node
    dead). The quorum must keep committing, the gray node must stay a
    peer (never treated as departed) and keep making progress through
    the leak, and once the links clear it must catch up hash-identical."""
    import asyncio

    churn = _churn_mod()

    async def run():
        net, nodes, _pvs, _genesis = await churn.build_fleet(5, seed=seed)
        gray = "full0"
        try:
            for nd in nodes.values():
                nd.cs.config.gossip_stall_refresh_s = 1.0
            from tendermint_tpu.p2p.inproc import plan_link_profiles

            plan = plan_link_profiles(sorted(nodes), "gray", seed=seed)
            plan = {lk: kw for lk, kw in plan.items() if gray in lk}
            applied = net.apply_link_plan(plan, seed=seed)
            assert applied == 8, applied  # 4 peers x 2 directions
            await churn._wait_heights(list(nodes.values()), 2, timeout=120)
            majority = [nd for n, nd in nodes.items() if n != gray]
            h0 = max(nd.height for nd in majority)
            await churn._wait_heights(majority, h0 + 3, timeout=300)
            # gray is a leak, not a blackhole: the node is still a peer
            # of every survivor and still advancing through the loss
            assert gray not in net.departed
            for nd in majority:
                assert gray in nd.switch.peers, \
                    f"{nd.name} dropped the gray node"
            assert nodes[gray].height > 0
            net.clear_policies()
            h1 = max(nd.height for nd in majority)
            await churn._wait_heights(list(nodes.values()), h1 + 1,
                                      timeout=180)
            common = min(nd.height for nd in nodes.values()) - 1
            hashes = {nd.block_store.load_block_meta(common).header.app_hash
                      for nd in nodes.values()}
            assert len(hashes) == 1, "hashes diverged across the gray link"
        finally:
            for nd in nodes.values():
                try:
                    await nd.stop()
                except Exception:
                    pass

    asyncio.run(run())


CELLS = {
    "device.batch_verify": cell_device_batch_verify,
    "device.lane": cell_device_lane,
    "device.vote_flush": cell_device_vote_flush,
    "wal.fsync": cell_wal_fsync,
    "db.write_batch": cell_db_write_batch,
    "net.drop": cell_net_drop,
    "ingest.backpressure": cell_ingest_backpressure,
    "ingest.mempool_full": cell_ingest_mempool_full,
    "net.corrupt": cell_net_corrupt,
    "statesync.lying_chunk": cell_statesync_lying_chunk,
    "statesync.lying_snapshot": cell_statesync_lying_snapshot,
    "blocksync.bad_block": cell_blocksync_bad_block,
    "lightserve.lying_server": cell_lightserve_lying_server,
    "combo.maverick_corrupt": cell_combo_maverick_corrupt,
    "churn.flap": cell_churn_flap,
    "churn.rotate": cell_churn_rotate,
    "churn.partition32": cell_churn_partition32,
    "churn.corrupt32": cell_churn_corrupt32,
    "net.quorum_loss": cell_net_quorum_loss,
    "net.asym": cell_net_asym,
    "net.gray": cell_net_gray,
    "exec.conflict_storm": cell_exec_conflict_storm,
    "aggsig.degrade": cell_aggsig_degrade,
    "crash.torn_wal": cell_crash_torn_wal,
    "crash.privval": cell_crash_privval,
    "crash.loop": cell_crash_loop,
    "soak.gameday": cell_soak_gameday,
}
assert set(CELLS) == set(SITES)


# -- matrix driver -----------------------------------------------------------

def run_cell_subprocess(site: str, seed: int, timeout: float = 300.0):
    """One cell in a fresh interpreter; returns (passed, seconds, detail)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TMTPU_FAULTS", None)  # the cell arms its own sites
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cell", site, "--seed", str(seed)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, time.perf_counter() - t0, "timeout"
    dt = time.perf_counter() - t0
    if proc.returncode == 0:
        return True, dt, ""
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return False, dt, tail[-1] if tail else f"exit {proc.returncode}"


def format_table(rows) -> str:
    """rows: (site, seed, passed, seconds, detail)."""
    header = ("site", "seed", "result", "secs", "detail")
    table = [header] + [(site, str(seed), "PASS" if ok else "FAIL",
                         f"{secs:.1f}", detail[:60])
                        for site, seed, ok, secs, detail in rows]
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def self_test() -> None:
    # table plumbing
    rows = [("wal.fsync", 1, True, 0.51, ""),
            ("net.drop", 2, False, 61.0, "divergent block hashes")]
    txt = format_table(rows)
    assert "PASS" in txt and "FAIL" in txt and "wal.fsync" in txt, txt
    assert txt.splitlines()[0].startswith("site"), txt
    # registry closed under CELLS/SITES (module asserts at import too)
    assert all(s in CELLS for s in SITES)
    # the cheapest cells in-process: the injection seams really work
    from tendermint_tpu.libs.faults import faults

    cell_db_write_batch(seed=1)
    faults.reset()
    cell_wal_fsync(seed=1)
    faults.reset()
    # the Byzantine statesync cells are jax-free and fast: run them too
    cell_statesync_lying_chunk(seed=1)
    faults.reset()
    cell_statesync_lying_snapshot(seed=1)
    faults.reset()
    # the lying light-server cell is jax-free (host-path ed25519): run it
    cell_lightserve_lying_server(seed=1)
    faults.reset()
    # churn plumbing: the plan the churn cells execute is deterministic
    churn = _churn_mod()
    assert churn.plan_churn(3, 2, 8) == churn.plan_churn(3, 2, 8)
    # degraded-net plumbing, 2 seeds each: the quorum-loss plan and the
    # link-profile plans the net.* cells execute are seed-deterministic
    # (the live fleets themselves run via the matrix — they are the slow
    # cells) and the planner invariants hold
    ql = _quorum_loss_mod()
    from tendermint_tpu.p2p.inproc import LINK_PROFILES, plan_link_profiles

    ids = [f"n{i}" for i in range(5)]
    for seed in (1, 2):
        plan = ql.plan_quorum_loss(seed, windows=2)
        assert plan == ql.plan_quorum_loss(seed, windows=2)
        assert ql.plan_fingerprint(plan) == ql.plan_fingerprint(
            ql.plan_quorum_loss(seed, windows=2))
        for ev in plan["events"]:
            assert ev["isolated_power"] * 3 > ev["total_power"], ev
            assert 0 < len(ev["isolate"]) < plan["n_validators"], ev
        for profile in LINK_PROFILES:
            lp = plan_link_profiles(ids, profile, seed=seed)
            assert lp == plan_link_profiles(ids, profile, seed=seed)
            assert all(kw["profile"] == profile for kw in lp.values())
        asym = plan_link_profiles(ids, "asym", seed=seed)
        assert all((dst, src) not in asym for (src, dst) in asym)
    assert ql.plan_quorum_loss(1, windows=2) != ql.plan_quorum_loss(
        2, windows=2)
    # the crash cells are jax-free and fast: run them in-process too
    cell_crash_torn_wal(seed=1)
    faults.reset()
    cell_crash_privval(seed=1)
    faults.reset()
    cell_crash_loop(seed=1)
    print("chaos_matrix self-test OK")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sites", default=",".join(SITES),
                    help="comma-separated subset of: " + ", ".join(SITES))
    ap.add_argument("--seeds", default=",".join(map(str, DEFAULT_SEEDS)))
    ap.add_argument("--quick", action="store_true",
                    help="skip slow cells (the in-proc consensus net)")
    ap.add_argument("--cell", help="(internal) run one cell in-process")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)

    if args.self_test:
        self_test()
        return 0
    if args.cell:
        if args.cell not in CELLS:
            ap.error(f"unknown cell {args.cell!r}")
        _pin_cpu_jax()
        CELLS[args.cell](args.seed)
        return 0

    sites = [s.strip() for s in args.sites.split(",") if s.strip()]
    unknown = [s for s in sites if s not in SITES]
    if unknown:
        ap.error(f"unknown sites: {unknown}")
    if args.quick:
        sites = [s for s in sites if not SITES[s]]
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]

    rows = []
    for site in sites:
        for seed in seeds:
            ok, secs, detail = run_cell_subprocess(site, seed)
            rows.append((site, seed, ok, secs, detail))
            print(f"{'PASS' if ok else 'FAIL'}  {site} seed={seed} "
                  f"({secs:.1f}s)", flush=True)
    print()
    print(format_table(rows))
    failed = [r for r in rows if not r[2]]
    print(f"\n{len(rows) - len(failed)}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
