"""Experiment B: dispatch sizing + pipelining for the 10k commit path.

  V4 window=1 serial (6 dispatches of 5 chunks)
  V5 window=2, double-buffered: worker thread packs+dispatches window i+1
     while the main thread fetches window i
  V6 window=1, 2-deep pipeline
  V7 window=2, chunk=4096 (K=5 per dispatch)
"""

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from bench import _mk_val_set, _sign_commit
from tendermint_tpu.crypto.ed25519_jax import verify as V


def main():
    n_vals, n_commits = 10240, 6
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    per_commit = []
    for c in commits:
        pks = [v.pub_key.bytes() for v in vs.validators]
        msgs = [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs = [cs.signature for cs in c.signatures]
        per_commit.append((pks, msgs, sigs))
    print("setup done", flush=True)

    def flat(cs):
        return ([p for c in cs for p in c[0]],
                [m for c in cs for m in c[1]],
                [s for c in cs for s in c[2]])

    n = n_commits * n_vals
    pool = ThreadPoolExecutor(max_workers=2)

    def serial(window, chunk):
        def run():
            for i in range(0, n_commits, window):
                pks, msgs, sigs = flat(per_commit[i:i + window])
                args, ok = V.prepare_sparse_stream(pks, msgs, sigs, chunk)
                out = np.asarray(V._verify_sparse_stream_kernel(*args))
                assert out.reshape(-1)[:len(pks)].all() and ok.all()
        return run

    def pipelined(window, chunk, depth=2):
        def run():
            def submit(i):
                pks, msgs, sigs = flat(per_commit[i:i + window])
                args, ok = V.prepare_sparse_stream(pks, msgs, sigs, chunk)
                return V._verify_sparse_stream_kernel(*args), ok, len(pks)

            idxs = list(range(0, n_commits, window))
            futs = []
            for i in idxs[:depth]:
                futs.append(pool.submit(submit, i))
            k = depth
            for _ in idxs:
                fut = futs.pop(0)
                dev, ok, npk = fut.result()
                if k < len(idxs):
                    futs.append(pool.submit(submit, idxs[k]))
                    k += 1
                out = np.asarray(dev)
                assert out.reshape(-1)[:npk].all() and ok.all()
        return run

    cases = [
        ("V4 window=1 serial", serial(1, 2048)),
        ("V5 window=2 pipelined", pipelined(2, 2048)),
        ("V6 window=1 pipelined", pipelined(1, 2048)),
        ("V7 window=2 chunk=4096", serial(2, 4096)),
        ("V3r window=2 serial (rerun)", serial(2, 2048)),
    ]
    for label, fn in cases:
        t0 = time.perf_counter()
        fn()
        print(f"{label}: warm {time.perf_counter()-t0:.1f}s", flush=True)
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        print(f"{label}: {best*1e3:7.1f} ms -> {n/best:8.0f} sigs/s "
              f"({n/best/5888:.2f}x est)", flush=True)


if __name__ == "__main__":
    main()
