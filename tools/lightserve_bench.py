"""Light-client serving-plane bench + stdlib-only self-test.

    python tools/lightserve_bench.py               # the serving A/B
    python tools/lightserve_bench.py --self-test   # pure planning math

The bench mode delegates to bench.py's lightserve helpers so this tool and
``python bench.py --config lightserve`` measure the IDENTICAL code path
(VerifyCoalescer batching a client fleet vs one scalar verifier.verify per
request). Rows use the same JSONL contract as bench.py.

The self-test needs NOTHING beyond the stdlib: it loads
``tendermint_tpu/light/serve.py`` by file path (the module keeps its
package imports lazy for exactly this) and checks the pure planning
contracts — the flush schedule the coalescer implements, the bisection
skeleton the prefetcher pins, the bounded fan-out queue math the ws plane
enforces, and the token-bucket/cache/limiter semantics — fast enough for
tools/selfcheck.py's per-tool timeout.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SERVE_PY = os.path.join(REPO, "tendermint_tpu", "light", "serve.py")


def _emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(value, 3), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _load_serve_standalone():
    """serve.py by file path — no package import, no third-party deps."""
    spec = importlib.util.spec_from_file_location("_lightserve_solo", SERVE_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def self_test() -> int:
    serve = _load_serve_standalone()

    # flush planning: the pure spec VerifyCoalescer implements
    assert serve.plan_flushes([0.0, 0.001, 0.002], 0.005, 64) == [(0.005, 3)]
    assert serve.plan_flushes([0.0, 0.001, 0.002], 0.005, 2) == \
        [(0.001, 2), (0.007, 1)]
    assert serve.plan_flushes([0.0, 1.0], 0.005, 64) == \
        [(0.005, 1), (1.005, 1)]
    assert serve.plan_flushes([], 0.005, 8) == []
    # size-vs-deadline crossover: a dense burst closes on size, the tail
    # on deadline — total batched == total arrivals, always
    arrivals = [i * 0.00005 for i in range(100)] + [1.0]
    plan = serve.plan_flushes(arrivals, 0.002, 32)
    assert sum(n for _, n in plan) == len(arrivals), plan
    assert max(n for _, n in plan) == 32

    # bisection skeleton: breadth-first midpoints, the order a bisecting
    # client walks the span; deterministic, deduped, capped
    sk = serve.bisection_skeleton(1, 17)
    assert sk[0] == 9 and sk[1:3] == [5, 13], sk
    assert len(sk) == len(set(sk))
    assert all(1 < h < 17 for h in sk)
    assert serve.bisection_skeleton(4, 5) == []
    assert len(serve.bisection_skeleton(1, 1 << 20, cap=16)) == 16
    assert serve.bisection_skeleton(1, 17) == serve.bisection_skeleton(1, 17)

    # fan-out queue bounds: backlog is capped, overflow evicts
    assert serve.fanout_queue_plan(10, 10, 4) == (0, False)
    assert serve.fanout_queue_plan(10, 7, 4) == (3, False)
    assert serve.fanout_queue_plan(10, 0, 4) == (4, True)

    # token bucket on an injected clock
    t = [0.0]
    tb = serve.TokenBucket(rate=2.0, burst=2.0, clock=lambda: t[0])
    assert tb.allow() and tb.allow() and not tb.allow()
    t[0] = 0.5
    assert tb.allow() and not tb.allow()

    # header cache: LRU with pinned skeleton entries, hard capacity
    c = serve.HeaderCache(capacity=3)
    c.put(1, "a")
    c.put(2, "b", pinned=True)
    c.put(3, "c")
    assert c.get(1) == "a"
    c.put(4, "d")
    assert c.peek(3) is None and c.peek(2) == "b"
    assert c.stats == {"hits": 1, "misses": 0, "evictions": 1}

    # client limiter: reason-labeled sheds, abuse scoring on a stub board
    class Board:
        def __init__(self):
            self.strikes = {}

        def banned(self, pid):
            return self.strikes.get(pid, 0) >= 2

        def record_failure(self, pid, reason="error", severe=False):
            self.strikes[pid] = self.strikes.get(pid, 0) + 1

        def record_success(self, pid):
            self.strikes[pid] = 0

    t[0] = 0.0
    lim = serve.ClientLimiter(rate=1.0, burst=1.0, scoreboard=Board(),
                              clock=lambda: t[0])
    lim.admit("c")
    reasons = []
    for _ in range(3):
        try:
            lim.admit("c")
        except serve.ShedError as e:
            reasons.append(e.reason)
    assert reasons == ["client-rate", "client-rate", "banned"], reasons

    print("lightserve_bench self-test OK (flush planning, skeleton math, "
          "fan-out bounds, cache/limiter semantics — stdlib only)")
    return 0


def run_bench(clients: int, spans: int) -> int:
    import bench

    blocks = bench._mk_light_serve_chain(16, 12, "lightserve-tool-ed")
    all_spans = [(1, 12), (2, 12), (1, 8), (3, 10), (2, 9), (4, 11)]
    use = all_spans[:max(1, min(spans, len(all_spans)))]
    per_span = max(1, clients // len(use))
    now_ns = 1_700_000_000_000_000_000 + 100 * 1_000_000_000
    reqs = bench._lightserve_requests(blocks, use, per_span, now_ns)

    bench._lightserve_run_scalar(reqs)  # warm
    bench._lightserve_run_coalesced(reqs)
    sc_wall, sc_lat = bench._lightserve_run_scalar(reqs)
    co_wall, co_lat, stats = bench._lightserve_run_coalesced(reqs)
    sc_rate, co_rate = len(reqs) / sc_wall, len(reqs) / co_wall
    _emit("lightserve_clients_headers_per_sec", co_rate, "headers/s",
          co_rate / sc_rate, clients=len(reqs), spans=len(use),
          scalar_headers_per_sec=round(sc_rate, 1),
          verified_requests=stats["verified_requests"],
          coalesced_dupes=stats["coalesced_dupes"],
          batched_sigs=stats["batched_sigs"])
    _emit("lightserve_p99_s", bench._p99(co_lat), "s",
          bench._p99(co_lat) / bench._p99(sc_lat),
          scalar_p99_s=round(bench._p99(sc_lat), 6))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=96,
                    help="fleet size for the serving A/B")
    ap.add_argument("--spans", type=int, default=6,
                    help="distinct (trusted, target) spans the fleet asks")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    return run_bench(args.clients, args.spans)


if __name__ == "__main__":
    sys.exit(main())
