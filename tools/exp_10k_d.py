"""Head-to-head under identical relay conditions: V5-style manual pipeline
vs the integrated _verify_segmented, interleaved A/B/A/B to cancel drift."""

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

from bench import _mk_val_set, _sign_commit
from tendermint_tpu.crypto.ed25519_jax import verify as V


def main():
    n_vals, n_commits = 10240, 6
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    per_commit = []
    for c in commits:
        pks = [v.pub_key.bytes() for v in vs.validators]
        msgs = [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs = [cs.signature for cs in c.signatures]
        per_commit.append((pks, msgs, sigs))
    apks = [p for c in per_commit for p in c[0]]
    amsgs = [m for c in per_commit for m in c[1]]
    asigs = [s for c in per_commit for s in c[2]]
    n = n_commits * n_vals
    pool = ThreadPoolExecutor(max_workers=2)
    print("setup done", flush=True)

    def flat(cs):
        return ([p for c in cs for p in c[0]],
                [m for c in cs for m in c[1]],
                [s for c in cs for s in c[2]])

    def v5():  # manual: window=2 commits, depth-2 pipeline
        def submit(i):
            pks, msgs, sigs = flat(per_commit[i:i + 2])
            args, ok = V.prepare_sparse_stream(pks, msgs, sigs, 2048)
            return V._verify_sparse_stream_kernel(*args), ok, len(pks)

        idxs = [0, 2, 4]
        futs = [pool.submit(submit, i) for i in idxs[:2]]
        k = 2
        for _ in idxs:
            dev, ok, npk = futs.pop(0).result()
            if k < len(idxs):
                futs.append(pool.submit(submit, idxs[k]))
                k += 1
            out = np.asarray(dev)
            assert out.reshape(-1)[:npk].all() and ok.all()

    def integrated():
        assert V.batch_verify_stream(apks, amsgs, asigs, chunk=2048).all()

    v5()
    integrated()
    ts = {"v5": [], "integrated": []}
    for _ in range(4):
        for name, fn in (("v5", v5), ("integrated", integrated)):
            t0 = time.perf_counter()
            fn()
            ts[name].append(time.perf_counter() - t0)
    for name, arr in ts.items():
        best = min(arr)
        print(f"{name:12s} min {best*1e3:7.1f} ms  med "
              f"{sorted(arr)[len(arr)//2]*1e3:7.1f} ms -> {n/best:8.0f} sigs/s",
              flush=True)


if __name__ == "__main__":
    main()
