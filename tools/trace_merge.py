"""Merge N nodes' Chrome trace JSONs into ONE Perfetto-loadable timeline.

Each node's tracer stamps its export with ``node_id`` and a wall↔perf epoch
pair (libs/trace.py set_identity). This tool re-bases every node's
perf_counter-domain timestamps onto the shared wall clock, gives each node
its own pid track (named via process_name metadata), and writes a single
trace where cross-node causality — proposal on node0, prevotes landing on
node1..3, commit spread — is visible on one screen:

    python tools/trace_merge.py node0.json node1.json ... --out merged.json
    python tools/trace_merge.py *.json                 # skew report only
    python tools/trace_merge.py --self-test            # CI guard

The skew report groups ``stage_commit_finalized`` spans (consensus
timeline, args.height) per height: first-to-last commit spread across
nodes, plus per-node slowest-stage attribution (which stage eats the most
mean wall-clock on each node).

Dependency-free on purpose (stdlib only): it must run against trace files
scp'd off a fleet onto a box that can't import jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_trace(path: str) -> dict:
    """Full trace document; bare event arrays are wrapped."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        data = {"traceEvents": data}
    if not isinstance(data, dict) or not isinstance(
            data.get("traceEvents", []), list):
        raise ValueError(f"{path}: not a trace-event JSON")
    return data


def node_label(doc: dict, path: str) -> str:
    label = doc.get("node_id")
    if label:
        return str(label)
    stem = os.path.splitext(os.path.basename(path))[0]
    return stem


def rebase_events(doc: dict) -> Tuple[List[dict], bool]:
    """Events with ``ts`` moved from the node's perf_counter domain onto
    the wall clock (unix microseconds). Returns (events, aligned): without
    an epoch header the events pass through untouched and aligned=False —
    the merge still renders, tracks just share no common zero."""
    events = [e for e in doc.get("traceEvents", [])
              if isinstance(e, dict) and e.get("ph") != "M"]
    epoch_unix = doc.get("epoch_unix_s")
    epoch_perf = doc.get("epoch_perf_us")
    if epoch_unix is None or epoch_perf is None:
        return [dict(e) for e in events], False
    base = float(epoch_unix) * 1e6 - float(epoch_perf)
    out = []
    for e in events:
        e2 = dict(e)
        e2["ts"] = float(e.get("ts", 0.0)) + base
        out.append(e2)
    return out, True


def merge(docs_with_labels: List[Tuple[str, dict]]) -> dict:
    """One merged Chrome trace: per-node pid tracks aligned on the wall
    clock, shifted so the earliest event sits at t=0."""
    tracks = []
    dropped_total = 0
    for label, doc in docs_with_labels:
        events, aligned = rebase_events(doc)
        dropped_total += int(doc.get("dropped", 0) or 0)
        tracks.append((label, events, aligned))
    any_aligned = any(aligned for _, _, aligned in tracks)

    def _min_ts(events: List[dict]) -> Optional[float]:
        return min((e["ts"] for e in events
                    if isinstance(e.get("ts"), (int, float))), default=None)

    # t=0 is the earliest ALIGNED event: an epoch-less track's private
    # perf-domain ts (tiny) must not drag the wall-clock tracks (~1.7e15us)
    # to a gigasecond offset that Perfetto fits into one sub-pixel view —
    # and neither must an aligned-but-EMPTY track (a node that died at
    # startup exports the header with no events; _min_ts -> None, skipped)
    aligned_mins = [m for m in (_min_ts(ev) for _, ev, aligned in tracks
                                if aligned) if m is not None]
    t0 = min(aligned_mins) if aligned_mins \
        else min((m for m in (_min_ts(ev) for _, ev, _ in tracks)
                  if m is not None), default=0.0)
    merged: List[dict] = []
    for pid, (label, events, aligned) in enumerate(tracks, start=1):
        name = label if aligned else f"{label} (unaligned)"
        # unaligned tracks rebase onto the merged origin by their OWN
        # first event — positions within the track stay truthful, only
        # the cross-track offset is arbitrary (hence the label)
        own_min = _min_ts(events)
        shift = t0 if aligned else (own_min if own_min is not None else 0.0)
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        for e in events:
            e["pid"] = pid
            if isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] - shift
            merged.append(e)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "aligned": any_aligned, "dropped": dropped_total,
            "nodes": [label for label, _, _ in tracks]}


# -- skew report --------------------------------------------------------------

def commit_times(docs_with_labels: List[Tuple[str, dict]]
                 ) -> Dict[int, Dict[str, float]]:
    """height -> {node -> wall-clock commit time (us)} from the stage
    timeline's ``stage_commit_finalized`` spans (span END = the commit
    mark)."""
    out: Dict[int, Dict[str, float]] = {}
    for label, doc in docs_with_labels:
        events, aligned = rebase_events(doc)
        if not aligned:
            # an epoch-less trace's ts stay in its private perf domain —
            # mixing them into wall-clock spread math would report the
            # perf/unix offset (~decades) as cross-node skew
            continue
        for e in events:
            if e.get("name") != "stage_commit_finalized":
                continue
            h = (e.get("args") or {}).get("height")
            if not isinstance(h, int):
                continue
            t = float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
            # keep the FIRST commit of a height per node (restarts re-commit)
            out.setdefault(h, {}).setdefault(label, t)
    return out


def skew_report(docs_with_labels: List[Tuple[str, dict]]) -> dict:
    commits = commit_times(docs_with_labels)
    per_height = []
    for h in sorted(commits):
        times = commits[h]
        if len(times) < 2:
            continue
        first = min(times, key=times.get)
        last = max(times, key=times.get)
        per_height.append({
            "height": h,
            "nodes": len(times),
            "first": first,
            "last": last,
            "spread_ms": round((times[last] - times[first]) / 1000.0, 3),
        })
    spreads = [r["spread_ms"] for r in per_height]
    # slowest-stage attribution: per node, mean duration per stage span
    slowest: Dict[str, dict] = {}
    for label, doc in docs_with_labels:
        stages: Dict[str, List[float]] = {}
        for e in doc.get("traceEvents", []):
            name = e.get("name", "")
            if not name.startswith("stage_") or e.get("ph") != "X":
                continue
            stages.setdefault(name[len("stage_"):], []).append(
                float(e.get("dur", 0.0)))
        if not stages:
            continue
        means = {s: sum(v) / len(v) for s, v in stages.items()}
        worst = max(means, key=means.get)
        slowest[label] = {
            "slowest_stage": worst,
            "mean_ms": round(means[worst] / 1000.0, 3),
            "stage_mean_ms": {s: round(m / 1000.0, 3)
                              for s, m in sorted(means.items())},
        }
    return {
        "heights": len(per_height),
        "mean_spread_ms": round(sum(spreads) / len(spreads), 3) if spreads
        else 0.0,
        "max_spread_ms": max(spreads) if spreads else 0.0,
        "per_height": per_height,
        "slowest_stage_per_node": slowest,
    }


def render_skew(report: dict) -> str:
    lines = [f"cross-node skew over {report['heights']} heights: "
             f"mean {report['mean_spread_ms']} ms, "
             f"max {report['max_spread_ms']} ms"]
    rows = sorted(report["per_height"], key=lambda r: -r["spread_ms"])[:10]
    if rows:
        lines.append(f"{'height':>7}  {'nodes':>5}  {'spread_ms':>10}  "
                     f"first -> last")
        for r in rows:
            lines.append(f"{r['height']:>7}  {r['nodes']:>5}  "
                         f"{r['spread_ms']:>10.3f}  "
                         f"{r['first']} -> {r['last']}")
    for node, s in sorted(report["slowest_stage_per_node"].items()):
        lines.append(f"{node}: slowest stage {s['slowest_stage']} "
                     f"(mean {s['mean_ms']} ms)")
    return "\n".join(lines)


# -- self-test ----------------------------------------------------------------

def _synthetic_doc(node_id: str, epoch_unix_s: float, epoch_perf_us: float,
                   commit_wall_us: Dict[int, float]) -> dict:
    """A node trace whose stage_commit_finalized spans END at the given
    WALL-clock times, expressed in that node's private perf domain."""
    events = []
    for h, wall_us in commit_wall_us.items():
        perf_end = wall_us - epoch_unix_s * 1e6 + epoch_perf_us
        events.append({"name": "stage_commit_finalized", "ph": "X",
                       "ts": perf_end - 2000.0, "dur": 2000.0, "pid": 9,
                       "tid": 1, "args": {"height": h, "round": 0}})
        events.append({"name": "stage_prevote_quorum", "ph": "X",
                       "ts": perf_end - 9000.0, "dur": 5000.0, "pid": 9,
                       "tid": 1, "args": {"height": h, "round": 0}})
    return {"traceEvents": events, "displayTimeUnit": "ms", "dropped": 0,
            "node_id": node_id, "epoch_unix_s": epoch_unix_s,
            "epoch_perf_us": epoch_perf_us}


def self_test() -> int:
    """Two synthetic nodes with WILDLY different perf_counter origins but a
    known 50ms wall-clock commit skew: the merge must align them and the
    skew report must read exactly 50ms."""
    base = 1_700_000_000.0  # unix seconds
    a = _synthetic_doc("node-a", base, 111_000_000.0,
                       {5: base * 1e6 + 1_000_000.0,
                        6: base * 1e6 + 2_000_000.0})
    b = _synthetic_doc("node-b", base + 100.0, 999_000_000.0,
                       {5: base * 1e6 + 1_050_000.0,
                        6: base * 1e6 + 2_050_000.0})
    docs = [("node-a", a), ("node-b", b)]
    merged = merge(docs)
    assert merged["aligned"] is True
    pids = {e["pid"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert pids == {1, 2}, pids
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M"]
    assert names == ["node-a", "node-b"], names
    # after rebasing, node-b's height-5 commit ends exactly 50ms after
    # node-a's, even though their raw perf ts differ by ~888 seconds
    ends = {}
    for e in merged["traceEvents"]:
        if e.get("name") == "stage_commit_finalized":
            ends.setdefault(e["args"]["height"], {})[e["pid"]] = (
                e["ts"] + e["dur"])
    assert abs((ends[5][2] - ends[5][1]) - 50_000.0) < 1.0, ends
    assert min(e.get("ts", 0.0) for e in merged["traceEvents"]
               if e.get("ph") == "X") == 0.0
    report = skew_report(docs)
    assert report["heights"] == 2
    assert abs(report["max_spread_ms"] - 50.0) < 0.001, report
    assert report["per_height"][0]["first"] == "node-a"
    assert report["per_height"][0]["last"] == "node-b"
    for node in ("node-a", "node-b"):
        assert report["slowest_stage_per_node"][node]["slowest_stage"] == \
            "prevote_quorum"
    assert "node-a -> node-b" in render_skew(report)
    # an epoch-less trace still merges, on an unaligned track
    bare = {"traceEvents": [{"name": "x", "ph": "X", "ts": 5.0, "dur": 1.0,
                             "pid": 1, "tid": 1}]}
    m2 = merge([("node-a", a), ("old", bare)])
    names = [e["args"]["name"] for e in m2["traceEvents"]
             if e.get("ph") == "M"]
    assert names == ["node-a", "old (unaligned)"], names
    # the unaligned track must not drag the aligned tracks' zero: node-a's
    # first event still sits at t=0 and the bare track rebases by its own
    # origin (5.0), keeping every ts in one renderable window
    m2_ts = {e.get("name"): e["ts"] for e in m2["traceEvents"]
             if e.get("ph") == "X"}
    assert m2_ts["x"] == 0.0, m2_ts
    assert min(e["ts"] for e in m2["traceEvents"]
               if e.get("ph") == "X") == 0.0
    assert max(e["ts"] for e in m2["traceEvents"]
               if e.get("ph") == "X") < 2e9, "mixed merge left a track "\
        "at a wall-clock offset"
    # an epoch-less trace must not feed the skew math either: its commit
    # spans sit in a private perf domain, not on the shared wall clock
    bare_commit = {"traceEvents": [
        {"name": "stage_commit_finalized", "ph": "X", "ts": 7.0,
         "dur": 1.0, "pid": 1, "tid": 1, "args": {"height": 5}}]}
    r3 = skew_report([("node-a", a), ("old", bare_commit)])
    assert r3["heights"] == 0, r3
    assert r3["max_spread_ms"] == 0.0, r3
    # an aligned trace with NO events (node died at startup: header only)
    # must not drag t0 to 0 and push healthy tracks to wall-clock offsets
    empty = {"traceEvents": [], "node_id": "dead",
             "epoch_unix_s": base, "epoch_perf_us": 0.0}
    m3 = merge([("node-a", a), ("dead", empty)])
    assert min(e["ts"] for e in m3["traceEvents"]
               if e.get("ph") == "X") == 0.0
    assert max(e["ts"] for e in m3["traceEvents"]
               if e.get("ph") == "X") < 2e9, "empty aligned track dragged t0"
    print("trace_merge self-test OK (2 nodes, 2 heights, 50.0 ms skew)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("traces", nargs="*",
                    help="per-node Chrome trace-event JSONs "
                         "(TMTPU_TRACE_OUT / bench --trace-out output)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the merged Perfetto-loadable trace here")
    ap.add_argument("--json", action="store_true",
                    help="print the skew report as JSON")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in alignment check and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if len(args.traces) < 2:
        ap.error("need at least two trace files (or --self-test)")
    docs = []
    for path in args.traces:
        doc = load_trace(path)
        docs.append((node_label(doc, path), doc))
    if args.out:
        merged = merge(docs)
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"wrote merged trace for {len(docs)} nodes to {args.out} "
              f"({len(merged['traceEvents'])} events, "
              f"aligned={merged['aligned']})")
    report = skew_report(docs)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_skew(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
