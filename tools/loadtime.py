#!/usr/bin/env python3
"""loadtime: tx load generator + latency report
(reference test/loadtime — txs embed send timestamps; the report tool reads
them back from committed blocks and prints latency percentiles).

Usage:
    python tools/loadtime.py load --endpoint http://127.0.0.1:26657 \
        --rate 50 --duration 10 --size 128
    python tools/loadtime.py report --endpoint http://127.0.0.1:26657
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import struct
import sys
import time
import urllib.request

MAGIC = b"ltm1"


def make_tx(size: int, seq: int) -> bytes:
    """MAGIC || send_time_ns (8B) || seq (8B) || padding."""
    body = MAGIC + struct.pack(">QQ", time.time_ns(), seq)
    return body + os.urandom(max(0, size - len(body)))


def parse_tx(tx: bytes):
    if not tx.startswith(MAGIC) or len(tx) < 20:
        return None
    send_ns, seq = struct.unpack(">QQ", tx[4:20])
    return send_ns, seq


async def load(endpoint: str, rate: float, duration: float, size: int) -> int:
    import aiohttp

    sent = ok = 0
    interval = 1.0 / rate if rate > 0 else 0.0
    deadline = time.monotonic() + duration
    async with aiohttp.ClientSession() as s:
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            tx = make_tx(size, sent)
            payload = {"jsonrpc": "2.0", "id": sent,
                       "method": "broadcast_tx_sync",
                       "params": {"tx": base64.b64encode(tx).decode()}}
            try:
                async with s.post(endpoint + "/", json=payload) as r:
                    doc = await r.json()
                if doc.get("result", {}).get("code", 1) == 0:
                    ok += 1
            except Exception as e:
                print(f"send error: {e}", file=sys.stderr)
            sent += 1
            sleep = interval - (time.monotonic() - t0)
            if sleep > 0:
                await asyncio.sleep(sleep)
    print(f"sent {sent} txs, {ok} accepted by CheckTx")
    return 0


def report(endpoint: str) -> int:
    """Walk committed blocks; latency = block time - embedded send time."""
    def rpc(path):
        with urllib.request.urlopen(endpoint + "/" + path, timeout=10) as r:
            return json.load(r)["result"]

    status = rpc("status")
    latest = int(status["sync_info"]["latest_block_height"])
    base = int(status["sync_info"]["earliest_block_height"]) or 1
    lats = []
    for h in range(base, latest + 1):
        blk = rpc(f"block?height={h}")
        header_time = blk["block"]["header"]["time"]
        from datetime import datetime, timezone

        ts = header_time.rstrip("Z")
        frac_ns = 0
        if "." in ts:
            ts, frac = ts.split(".", 1)
            frac_ns = int(frac[:9].ljust(9, "0"))
        block_ns = int(datetime.fromisoformat(ts).replace(
            tzinfo=timezone.utc).timestamp()) * 10**9 + frac_ns
        for raw in blk["block"]["data"]["txs"]:
            parsed = parse_tx(base64.b64decode(raw))
            if parsed is None:
                continue
            send_ns, _seq = parsed
            lats.append((block_ns - send_ns) / 1e9)
    if not lats:
        print("no loadtime txs found in committed blocks")
        return 1
    lats.sort()

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    print(json.dumps({
        "txs": len(lats),
        "latency_s": {"min": round(lats[0], 4), "p50": round(pct(0.5), 4),
                      "p90": round(pct(0.9), 4), "p99": round(pct(0.99), 4),
                      "max": round(lats[-1], 4)},
    }))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="loadtime")
    sub = p.add_subparsers(dest="command", required=True)
    lp = sub.add_parser("load")
    lp.add_argument("--endpoint", default="http://127.0.0.1:26657")
    lp.add_argument("--rate", type=float, default=50.0)
    lp.add_argument("--duration", type=float, default=10.0)
    lp.add_argument("--size", type=int, default=128)
    rp = sub.add_parser("report")
    rp.add_argument("--endpoint", default="http://127.0.0.1:26657")
    ns = p.parse_args(argv)
    if ns.command == "load":
        return asyncio.run(load(ns.endpoint, ns.rate, ns.duration, ns.size))
    return report(ns.endpoint)


if __name__ == "__main__":
    sys.exit(main())
