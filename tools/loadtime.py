#!/usr/bin/env python3
"""loadtime: open-loop tx load harness + latency-percentile report
(reference test/loadtime, rebuilt open-loop: send times are PRE-PLANNED on
a fixed-rate schedule, so a stalled node cannot slow the offered load down
and hide its own latency — the coordinated-omission trap closed-loop
generators fall into. Latency is measured from each tx's PLANNED send
time, embedded in the tx itself and recovered from committed blocks.)

    # offered load: 4 clients, 50 tx/s for 10 s, 128-byte txs
    python tools/loadtime.py load --endpoint http://127.0.0.1:26657 \
        --rate 50 --duration 10 --size 128 --clients 4
    # recover per-tx latency from committed blocks (+ optional scrapes)
    python tools/loadtime.py report --endpoint http://127.0.0.1:26657 \
        --metrics-endpoint http://127.0.0.1:26660/metrics
    # both, one shot (what bench.py --config ingest drives)
    python tools/loadtime.py run --endpoint http://127.0.0.1:26657
    python tools/loadtime.py --self-test

The report walks committed blocks newest-known-first, parses every harness
tx (MAGIC || planned_send_ns || seq), and prints sustained committed txs/s
plus p50 / p99 / p99.9 end-to-end latency. When the node carries the
ingestion observability plane it also scrapes ``/tx_timeline`` (per-stage
lifecycle decomposition measured IN the node) and ``/metrics`` (mempool
admission/rejection counters, RPC endpoint latencies) so one run yields
the full trade-curve row.

Stdlib-only except the load path, which uses aiohttp when available and
falls back to thread-pooled urllib otherwise; --self-test is pure stdlib.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import struct
import sys
import time
import urllib.request
from typing import Dict, List, Optional

MAGIC = b"ltm1"
#: latency percentiles the report prints (p50/p99/p99.9 are the gate rows)
PERCENTILES = (0.5, 0.9, 0.99, 0.999)

#: the ingest plane's signed-tx envelope framing (mempool/ingest.py —
#: kept in sync by its tests): magic | pubkey(32) | fee(8) | nonce(8) |
#: payload | sig(64). The report strips it so --signed runs recover the
#: same harness payload from committed blocks; building one needs the
#: repo's crypto (the only non-stdlib corner besides aiohttp).
STX_MAGIC = b"stx1"
_STX_HEADER = 4 + 32 + 8 + 8
_STX_MIN = _STX_HEADER + 64


# -- tx format ----------------------------------------------------------------

def make_tx(size: int, seq: int, send_ns: Optional[int] = None) -> bytes:
    """MAGIC || send_time_ns (8B) || seq (8B) || deterministic padding.
    ``send_ns`` is the PLANNED send time (open-loop contract); padding is
    seq-derived so every tx is unique without an os.urandom syscall per tx
    at high rates."""
    if send_ns is None:
        send_ns = time.time_ns()
    body = MAGIC + struct.pack(">QQ", send_ns, seq)
    pad = max(0, size - len(body))
    if pad:
        body += (struct.pack(">Q", seq * 0x9E3779B97F4A7C15 % 2**64)
                 * (pad // 8 + 1))[:pad]
    return body


def strip_envelope(tx: bytes) -> bytes:
    """The harness payload inside a signed ingest envelope (or the tx
    itself when unsigned)."""
    if tx.startswith(STX_MAGIC) and len(tx) >= _STX_MIN:
        return tx[_STX_HEADER:-64]
    return tx


def parse_tx(tx: bytes):
    tx = strip_envelope(tx)
    if not tx.startswith(MAGIC) or len(tx) < 20:
        return None
    send_ns, seq = struct.unpack(">QQ", tx[4:20])
    return send_ns, seq


def make_signed_txs(size: int, scheds_ns, fee: int = 1,
                    n_keys: int = 4) -> list:
    """Pre-signed envelope txs for every schedule slot, built BEFORE the
    open-loop clock starts (pure-python ed25519 signing is ~2 ms/tx — on
    the schedule it would read as node latency). Slots rotate across
    ``n_keys`` ephemeral senders so per-sender lanes and rate limits see
    real traffic shape. Needs the repo on PYTHONPATH (only this load
    path does; the report/parse side stays stdlib)."""
    # the canonical encoder, not a re-implementation: envelope drift
    # would otherwise silently turn every signed run into 100% rejects
    from tendermint_tpu import crypto  # lazy: load path only
    from tendermint_tpu.mempool.ingest import make_signed_tx

    keys = [crypto.Ed25519PrivKey.generate(
        struct.pack(">Q", 0x10ad + i) * 4) for i in range(n_keys)]
    return [make_signed_tx(keys[seq % n_keys], make_tx(size, seq, send_ns),
                           nonce=seq, fee=fee)
            for seq, send_ns in enumerate(scheds_ns)]


# -- schedule + percentile math ----------------------------------------------

def plan_schedule(rate: float, n: int, t0: float = 0.0) -> List[float]:
    """n send times on a fixed-rate grid starting at t0. Planned BEFORE any
    tx is sent: the i-th send happens at t0 + i/rate no matter how slow
    the node answered tx i-1."""
    if rate <= 0:
        raise ValueError("rate must be > 0")
    return [t0 + i / rate for i in range(n)]


def percentiles(lats: List[float], ps=PERCENTILES) -> Dict[str, float]:
    """Nearest-rank percentiles over a latency list (seconds)."""
    if not lats:
        return {}
    s = sorted(lats)
    out = {"min": s[0], "max": s[-1],
           "mean": sum(s) / len(s)}
    for p in ps:
        label = ("p" + repr(p * 100).rstrip("0").rstrip(".")).replace(
            "p100", "max")
        out[label] = s[min(len(s) - 1, int(p * len(s)))]
    return out


# -- load (open loop) ---------------------------------------------------------

def _payload(seq: int, tx: bytes) -> bytes:
    return json.dumps({
        "jsonrpc": "2.0", "id": seq, "method": "broadcast_tx_sync",
        "params": {"tx": base64.b64encode(tx).decode()}}).encode()


async def open_loop_load(endpoint: str, rate: float, duration: float,
                         size: int, clients: int = 4,
                         signed: bool = False) -> dict:
    """Drive ``rate`` tx/s for ``duration`` s through ``clients`` concurrent
    senders. Client c owns schedule slots c, c+clients, ... — a slow
    response delays only that client's later slots, and the report still
    measures every tx from its PLANNED time, so any harness lag shows up
    as latency (and in ``max_sched_lag_s``), never as hidden load.
    ``signed`` wraps every tx in the ingest plane's ed25519 envelope
    (pre-signed before the clock starts)."""
    n = max(1, int(rate * duration))
    clients = max(1, min(clients, n))
    # schedule starts in the future so slot 0 is real; signed runs lead
    # far enough to pre-sign every tx first (pure-python ed25519 ~2 ms/tx
    # — overruns surface honestly in max_sched_lag_s, never hidden)
    lead = 0.0035 * n + 0.5 if signed else 0.2
    t0 = time.monotonic() + lead
    wall0 = time.time_ns() + int(lead * 1e9)
    sched = plan_schedule(rate, n, t0)
    prebuilt = None
    if signed:
        prebuilt = make_signed_txs(
            size, [wall0 + int(i / rate * 1e9) for i in range(n)])
    stats = {"planned": n, "sent": 0, "accepted": 0, "rejected": 0,
             "errors": 0, "max_sched_lag_s": 0.0}

    try:
        import aiohttp
    except ImportError:
        aiohttp = None

    async def drive(post):
        async def client(ci: int) -> None:
            for seq in range(ci, n, clients):
                target = sched[seq]
                now = time.monotonic()
                if target > now:
                    await asyncio.sleep(target - now)
                else:
                    stats["max_sched_lag_s"] = max(
                        stats["max_sched_lag_s"], now - target)
                if prebuilt is not None:
                    tx = prebuilt[seq]
                else:
                    planned_ns = wall0 + int((sched[seq] - t0) * 1e9)
                    tx = make_tx(size, seq, planned_ns)
                stats["sent"] += 1
                try:
                    code = await post(seq, tx)
                except Exception:
                    stats["errors"] += 1
                    continue
                if code == 0:
                    stats["accepted"] += 1
                else:
                    stats["rejected"] += 1

        await asyncio.gather(*(client(c) for c in range(clients)))

    if aiohttp is not None:
        # bounded like the urllib fallback: a wedged node must show up as
        # errors + planned-time latency, not stall a client slot for
        # aiohttp's 5-minute default
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10)) as session:
            async def post(seq, tx):
                async with session.post(
                        endpoint + "/", data=_payload(seq, tx),
                        headers={"Content-Type": "application/json"}) as r:
                    doc = await r.json(content_type=None)
                return int((doc.get("result") or {}).get("code", 1))

            await drive(post)
    else:
        loop = asyncio.get_running_loop()

        def post_sync(seq, tx):
            req = urllib.request.Request(
                endpoint + "/", data=_payload(seq, tx),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                doc = json.load(r)
            return int((doc.get("result") or {}).get("code", 1))

        async def post(seq, tx):
            return await loop.run_in_executor(None, post_sync, seq, tx)

        await drive(post)

    stats["offered_rate"] = rate
    stats["duration_s"] = duration
    stats["clients"] = clients
    stats["size_bytes"] = size
    stats["signed"] = bool(signed)
    return stats


def load(endpoint: str, rate: float, duration: float, size: int,
         clients: int = 4, signed: bool = False) -> int:
    stats = asyncio.run(open_loop_load(endpoint, rate, duration, size,
                                       clients, signed=signed))
    print(json.dumps(stats))
    return 0 if stats["errors"] < stats["planned"] else 1


# -- report -------------------------------------------------------------------

def _rpc_get(endpoint: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(endpoint + "/" + path, timeout=timeout) as r:
        return json.load(r)["result"]


def parse_block_time_ns(header_time: str) -> int:
    """RFC3339 header time -> unix ns."""
    from datetime import datetime, timezone

    ts = header_time.rstrip("Z")
    frac_ns = 0
    if "." in ts:
        ts, frac = ts.split(".", 1)
        frac_ns = int(frac[:9].ljust(9, "0"))
    return int(datetime.fromisoformat(ts).replace(
        tzinfo=timezone.utc).timestamp()) * 10**9 + frac_ns


def latencies_from_blocks(blocks: List[dict]):
    """Per-tx latency from block docs ({"block": {"header", "data"}}):
    block time minus the embedded PLANNED send time. Returns
    (latencies_s, first_block_ns, last_block_ns, n_txs)."""
    lats: List[float] = []
    first_ns = last_ns = None
    for blk in blocks:
        block_ns = parse_block_time_ns(blk["block"]["header"]["time"])
        found = False
        for raw in blk["block"]["data"]["txs"]:
            parsed = parse_tx(base64.b64decode(raw))
            if parsed is None:
                continue
            send_ns, _seq = parsed
            lats.append((block_ns - send_ns) / 1e9)
            found = True
        if found:
            first_ns = block_ns if first_ns is None else min(first_ns,
                                                             block_ns)
            last_ns = block_ns if last_ns is None else max(last_ns, block_ns)
    return lats, first_ns, last_ns, len(lats)


def summarize_timeline(doc: dict) -> dict:
    """Roll the /tx_timeline records up: per-stage stamp counts, and
    percentile stats over the node-measured total_s of committed records
    (the in-node broadcast→commit truth, immune to clock skew between the
    harness and the node)."""
    records = doc.get("records", [])
    stage_counts: Dict[str, int] = {}
    commit_s = []
    admission_s = []
    complete = 0
    for rec in records:
        marks = {m[0]: m[1] for m in rec.get("marks", [])}
        for s in marks:
            stage_counts[s] = stage_counts.get(s, 0) + 1
        if "rpc_received" in marks and "mempool_admitted" in marks:
            # admission latency: RPC front door -> lane insertion, the
            # in-node CheckTx-path cost the ingest bench gates as
            # localnet_4node_ingest_checktx_p99_s
            admission_s.append(
                max(0.0, marks["mempool_admitted"] - marks["rpc_received"]))
        if rec.get("terminal") == "committed":
            commit_s.append(rec.get("total_s", 0.0))
            if {"rpc_received", "checktx_done", "mempool_admitted",
                    "committed"} <= marks.keys():
                complete += 1
    return {
        "records": len(records),
        "sealed_total": doc.get("sealed_total", 0),
        "sample_rate": doc.get("sample_rate"),
        "stage_counts": stage_counts,
        "complete_rpc_to_commit_records": complete,
        "node_commit_latency_s": percentiles(commit_s),
        "admission_latency_s": percentiles(admission_s),
    }


def scrape_prom(text: str, wanted_prefixes=("tendermint_mempool_",
                                            "tendermint_rpc_")) -> dict:
    """{series: value} for the ingestion-plane series (histogram buckets
    skipped — sums/counts/counters/gauges carry the report)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if not name.startswith(wanted_prefixes) or name.endswith("_bucket"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            out[series] = float(value)
        except ValueError:
            continue
    return out


#: the series whose reason labels summarize_rejections rolls up: every
#: way the ingestion plane refuses or drops load (admission-control
#: sheds, pre-admission failures, post-admission evictions)
_REJECTION_SERIES = ("tendermint_mempool_shed_txs_total",
                     "tendermint_mempool_failed_txs",
                     "tendermint_mempool_evicted_txs_total")


def summarize_rejections(metrics: Dict[str, float]) -> Dict[str, dict]:
    """{series-kind: {reason: count}} from a /metrics scrape — dropped
    load rendered next to the latency percentiles, so a report can never
    show a rosy p99 while the node quietly shed half the offered txs."""
    out: Dict[str, dict] = {}
    for series, value in metrics.items():
        name, _, labels = series.partition("{")
        if name not in _REJECTION_SERIES or not value:
            continue
        reason = "total"
        for part in labels.rstrip("}").split(","):
            k, _, v = part.partition("=")
            if k == "reason":
                reason = v.strip('"')
        kind = name.rsplit("tendermint_mempool_", 1)[-1]
        out.setdefault(kind, {})[reason] = value
    return out


def report_doc(endpoint: str, metrics_endpoint: Optional[str] = None,
               max_blocks: int = 2000) -> dict:
    """Walk committed blocks + scrape the observability surfaces; the dict
    bench.py --config ingest turns into its two gated metric lines."""
    status = _rpc_get(endpoint, "status")
    latest = int(status["sync_info"]["latest_block_height"])
    base = max(1, int(status["sync_info"]["earliest_block_height"] or 1),
               latest - max_blocks + 1)
    blocks = []
    for h in range(base, latest + 1):
        blocks.append(_rpc_get(endpoint, f"block?height={h}"))
    lats, first_ns, last_ns, n_txs = latencies_from_blocks(blocks)
    doc: dict = {"blocks_scanned": len(blocks), "txs": n_txs}
    if lats:
        span_s = (last_ns - first_ns) / 1e9
        doc["commit_window_s"] = round(span_s, 3)
        if span_s > 0:
            # sustained rate over the commit window (first to last block
            # carrying harness txs)
            doc["txs_per_sec"] = round(n_txs / span_s, 3)
        # a single-block burst has NO window: emitting the raw count as a
        # rate would poison the higher-better bench gate — leave the key
        # absent so callers fail loud instead of recording a fiction
        doc["latency_s"] = {k: round(v, 4)
                            for k, v in percentiles(lats).items()}
    try:
        doc["tx_timeline"] = summarize_timeline(
            _rpc_get(endpoint, "tx_timeline?limit=200"))
    except Exception as e:
        doc["tx_timeline"] = {"error": f"{type(e).__name__}: {e}"}
    if metrics_endpoint:
        try:
            with urllib.request.urlopen(metrics_endpoint, timeout=10) as r:
                doc["metrics"] = scrape_prom(r.read().decode())
            doc["rejections"] = summarize_rejections(doc["metrics"])
        except Exception as e:
            doc["metrics"] = {"error": f"{type(e).__name__}: {e}"}
    return doc


def report(endpoint: str, metrics_endpoint: Optional[str] = None) -> int:
    doc = report_doc(endpoint, metrics_endpoint)
    print(json.dumps(doc, indent=1))
    return 0 if doc["txs"] else 1


# -- self-test ----------------------------------------------------------------

def _synthetic_node(n_blocks: int = 4, rate: float = 100.0):
    """A stdlib HTTP server imitating the RPC surface the report walks:
    /status, /block?height=N with harness txs, /tx_timeline, /metrics."""
    import http.server
    import threading

    t0_ns = 1_700_000_000 * 10**9
    blocks = {}
    seq = 0
    for h in range(1, n_blocks + 1):
        block_ns = t0_ns + h * 10**9
        txs = []
        for _ in range(int(rate) // n_blocks):
            # sent 0.35 s before its block committed
            txs.append(base64.b64encode(
                make_tx(64, seq, block_ns - 350_000_000)).decode())
            seq += 1
        blocks[h] = {"block": {
            "header": {"time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(block_ns // 10**9))
                + ".%09dZ" % (block_ns % 10**9)},
            "data": {"txs": txs}}}
    timeline = {"enabled": True, "sample_rate": 1.0, "active": 0,
                "sealed_total": seq, "records": [
                    {"key": "ab" * 32, "terminal": "committed", "height": 2,
                     "total_s": 0.31, "rechecks": 0,
                     "marks": [["rpc_received", 1.0], ["checktx_done", 1.1],
                               ["mempool_admitted", 1.1],
                               ["first_gossip", 1.15],
                               ["proposal_included", 1.2],
                               ["committed", 1.31]],
                     "durations": {"rpc_received": 0.0,
                                   "checktx_done": 0.1}}]}
    metrics_text = "\n".join([
        "# TYPE tendermint_mempool_admitted_txs_total counter",
        "tendermint_mempool_admitted_txs_total %d" % seq,
        'tendermint_mempool_failed_txs{reason="full"} 3',
        'tendermint_mempool_failed_txs{reason="invalid-sig"} 2',
        'tendermint_mempool_shed_txs_total{reason="queue-full"} 5',
        'tendermint_mempool_shed_txs_total{reason="sender-rate"} 0',
        'tendermint_mempool_evicted_txs_total{reason="priority-evicted"} 1',
        'tendermint_mempool_tx_stage_seconds_bucket{le="+Inf",stage="committed"} 9',
        'tendermint_rpc_request_seconds_count{endpoint="broadcast_tx_sync",outcome="ok"} %d' % seq,
    ]) + "\n"

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/status"):
                body = {"result": {"sync_info": {
                    "latest_block_height": str(n_blocks),
                    "earliest_block_height": "1"}}}
            elif self.path.startswith("/block?height="):
                h = int(self.path.split("=", 1)[1])
                body = {"result": blocks[h]}
            elif self.path.startswith("/tx_timeline"):
                body = {"result": timeline}
            elif self.path.startswith("/metrics"):
                data = metrics_text.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            else:
                self.send_response(404)
                self.end_headers()
                return
            data = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def self_test() -> int:
    # tx roundtrip: planned send time and seq survive; padding exact
    tx = make_tx(128, 7, 123456789)
    assert len(tx) == 128 and parse_tx(tx) == (123456789, 7)
    assert parse_tx(b"nope") is None
    assert len(make_tx(8, 1)) == 20  # never truncated below the header
    # two txs with the same seq differ only in send time; different seqs
    # differ in padding too (unique on the wire)
    assert make_tx(64, 1, 5) != make_tx(64, 2, 5)
    # a signed-envelope wrapping is transparent to the report (stdlib
    # fake: framing only, no real signature needed to parse)
    wrapped = STX_MAGIC + b"\xaa" * 32 + struct.pack(">QQ", 1, 7) \
        + tx + b"\xbb" * 64
    assert strip_envelope(wrapped) == tx
    assert parse_tx(wrapped) == (123456789, 7)
    assert strip_envelope(b"stx1short") == b"stx1short"  # malformed: as-is

    # open-loop schedule: exact fixed-rate grid, planned up front
    sched = plan_schedule(50.0, 100, t0=10.0)
    assert len(sched) == 100 and sched[0] == 10.0
    deltas = [b - a for a, b in zip(sched, sched[1:])]
    assert all(abs(d - 0.02) < 1e-9 for d in deltas), "grid not fixed-rate"

    # percentile math: nearest-rank on a known ladder
    p = percentiles([i / 100.0 for i in range(1, 101)])
    assert abs(p["p50"] - 0.51) < 1e-9 and abs(p["p99"] - 1.0) < 1e-9
    assert abs(p["p99.9"] - 1.0) < 1e-9 and p["min"] == 0.01
    assert percentiles([]) == {}

    # block-walk aggregation against synthetic docs
    srv = _synthetic_node()
    try:
        ep = f"http://127.0.0.1:{srv.server_address[1]}"
        doc = report_doc(ep, metrics_endpoint=ep + "/metrics")
        assert doc["txs"] == 100, doc
        assert abs(doc["latency_s"]["p50"] - 0.35) < 0.01, doc
        assert abs(doc["latency_s"]["p99.9"] - 0.35) < 0.01, doc
        # 100 txs across blocks 1..4 committed over a 3 s span
        assert abs(doc["txs_per_sec"] - 100 / 3.0) < 0.5, doc
        tlr = doc["tx_timeline"]
        assert tlr["complete_rpc_to_commit_records"] == 1, tlr
        assert tlr["stage_counts"]["committed"] == 1
        assert abs(tlr["node_commit_latency_s"]["p50"] - 0.31) < 1e-6
        # in-node admission latency (rpc_received -> mempool_admitted wall
        # delta over the timeline records) — the checktx-p99 gate's source
        adm = tlr["admission_latency_s"]
        assert abs(adm["p50"] - 0.1) < 1e-6 and abs(adm["p99"] - 0.1) < 1e-6
        mtx = doc["metrics"]
        assert mtx["tendermint_mempool_admitted_txs_total"] == 100.0
        assert mtx['tendermint_mempool_failed_txs{reason="full"}'] == 3.0
        assert not any("_bucket{" in s or s.endswith("_bucket")
                       for s in mtx), \
            "histogram bucket leaked into the scrape"
        # dropped load is first-class in the report: reason-labeled
        # sheds/failures/evictions rolled up next to the percentiles
        rej = doc["rejections"]
        assert rej["shed_txs_total"] == {"queue-full": 5.0}  # zeros dropped
        assert rej["failed_txs"] == {"full": 3.0, "invalid-sig": 2.0}
        assert rej["evicted_txs_total"] == {"priority-evicted": 1.0}
    finally:
        srv.shutdown()
    print("loadtime self-test OK (schedule, percentiles, report, scrapes)")
    return 0


# -- CLI ----------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="loadtime",
                                description=__doc__.split("\n")[0])
    p.add_argument("--self-test", action="store_true")
    sub = p.add_subparsers(dest="command")
    for name in ("load", "run"):
        sp = sub.add_parser(name)
        sp.add_argument("--endpoint", default="http://127.0.0.1:26657")
        sp.add_argument("--rate", type=float, default=50.0)
        sp.add_argument("--duration", type=float, default=10.0)
        sp.add_argument("--size", type=int, default=128)
        sp.add_argument("--clients", type=int, default=4)
        sp.add_argument("--signed", action="store_true",
                        help="wrap txs in the ingest plane's ed25519 "
                             "envelope (pre-signed; needs the repo on "
                             "PYTHONPATH)")
        if name == "run":
            sp.add_argument("--metrics-endpoint", default=None)
            sp.add_argument("--settle", type=float, default=4.0,
                            help="seconds to wait after load for tail "
                                 "txs to commit before the report")
    rp = sub.add_parser("report")
    rp.add_argument("--endpoint", default="http://127.0.0.1:26657")
    rp.add_argument("--metrics-endpoint", default=None)
    ns = p.parse_args(argv)
    if ns.self_test:
        return self_test()
    if ns.command is None:
        p.error("need a command (load/report/run) or --self-test")
    if ns.command == "load":
        return load(ns.endpoint, ns.rate, ns.duration, ns.size, ns.clients,
                    signed=ns.signed)
    if ns.command == "run":
        stats = asyncio.run(open_loop_load(ns.endpoint, ns.rate, ns.duration,
                                           ns.size, ns.clients,
                                           signed=ns.signed))
        time.sleep(ns.settle)
        doc = report_doc(ns.endpoint, ns.metrics_endpoint)
        doc["load"] = stats
        print(json.dumps(doc, indent=1))
        return 0 if doc["txs"] else 1
    return report(ns.endpoint, ns.metrics_endpoint)


if __name__ == "__main__":
    sys.exit(main())
