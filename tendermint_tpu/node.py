"""Node assembly: wires storage → ABCI proxy → handshake → reactors →
switch → RPC from a Config (reference node/node.go:706 NewNode DI assembly,
:941 OnStart ordering, :100 DefaultNewNode).

Usage:
    node = Node.default(config)     # loads node key, FilePV, genesis
    await node.start()              # transport listen, dial peers, RPC
    ...
    await node.stop()
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Dict, List, Optional

from .abci.application import Application
from .abci.example.kvstore import KVStoreApplication
from .blockchain.reactor import BlockchainReactor
from .config import Config
from .consensus import ConsensusState, WAL
from .consensus.reactor import ConsensusReactor
from .consensus.replay import Handshaker
from .evidence.pool import EvidencePool
from .evidence.reactor import EvidenceReactor
from .libs.db import DB, MemDB, SQLiteDB
from .mempool import CListMempool
from .mempool.reactor import MempoolReactor
from .p2p import NodeInfo, NodeKey, Switch, TCPTransport, parse_peer_list
from .p2p.conn.mconnection import MConnConfig
from .privval.file_pv import FilePV
from .proxy import AppConns, local_client_creator, socket_client_creator
from .state import BlockExecutor, StateStore, state_from_genesis
from .store import BlockStore
from .types import GenesisDoc
from .types.event_bus import EventBus
from .types.priv_validator import PrivValidator

logger = logging.getLogger("tmtpu.node")

# built-in ABCI apps resolvable by name from config.base.proxy_app
def _snapshot_kvstore():
    from .abci.example.kvstore import SnapshotKVStoreApplication

    return SnapshotKVStoreApplication()


def _merkle_kvstore():
    from .abci.example.kvstore import MerkleKVStoreApplication

    return MerkleKVStoreApplication()


BUILTIN_APPS = {
    "kvstore": KVStoreApplication,
    "kvstore-snapshot": _snapshot_kvstore,
    "kvstore-merkle": _merkle_kvstore,
}


def _make_db(backend: str, directory: str, name: str) -> DB:
    if backend == "mem":
        return MemDB()
    os.makedirs(directory, exist_ok=True)
    return SQLiteDB(os.path.join(directory, f"{name}.db"))


class Node:
    """(node/node.go:225 Node)"""

    def __init__(self, config: Config, priv_validator: Optional[PrivValidator],
                 node_key: NodeKey, genesis: GenesisDoc,
                 app: Optional[Application] = None):
        import time as _time

        # recovery clock: assembly → consensus-ready is the measurable
        # recovery duration (stores + handshake + WAL replay + start)
        self._boot_t0 = _time.monotonic()
        self.config = config
        self.genesis = genesis
        self.node_key = node_key

        # -- databases (node.go:235 initDBs) --------------------------------
        backend = config.base.db_backend
        dbdir = config.db_dir()
        self.block_store = BlockStore(_make_db(backend, dbdir, "blockstore"))
        self.state_store = StateStore(_make_db(backend, dbdir, "state"))

        # -- ABCI app + proxy (node.go:251) ---------------------------------
        if app is not None:
            creator = local_client_creator(app)
        elif config.base.abci == "socket":
            creator = socket_client_creator(config.base.proxy_app)
        elif config.base.abci == "grpc":
            from .proxy import grpc_client_creator

            creator = grpc_client_creator(config.base.proxy_app)
        else:
            app_cls = BUILTIN_APPS.get(config.base.proxy_app)
            if app_cls is None:
                raise ValueError(
                    f"unknown built-in app {config.base.proxy_app!r}; pass an "
                    "Application or use abci=socket")
            app = app_cls()
            creator = local_client_creator(app)
        self.app = app
        self.proxy_app = AppConns(creator)
        self.proxy_app.start()

        # -- state load + ABCI handshake (node.go:725,777) ------------------
        state = state_from_genesis(genesis)
        loaded = self.state_store.load()
        if loaded is not None:
            state = loaded
        self.event_bus = EventBus()
        handshaker = Handshaker(self.state_store, state, self.block_store,
                                genesis, exec_config=config.execution)
        state = handshaker.handshake(self.proxy_app.consensus, self.proxy_app.query)
        self.state_store.save(state)
        self.initial_state = state

        # -- mempool (node.go:368) ------------------------------------------
        mp_cfg = config.mempool
        mp_common = dict(
            height=state.last_block_height, max_txs=mp_cfg.size,
            max_txs_bytes=mp_cfg.max_txs_bytes,
            max_tx_bytes=mp_cfg.max_tx_bytes, cache_size=mp_cfg.cache_size,
            keep_invalid_txs_in_cache=mp_cfg.keep_invalid_txs_in_cache,
            recheck=mp_cfg.recheck)
        self.ingest = None
        if mp_cfg.version == "v0":
            self.mempool = CListMempool(self.proxy_app.mempool, **mp_common)
        else:
            # the ingestion fast path (mempool/ingest.py): sharded
            # per-sender lanes behind the same surface, plus the async
            # admission pipeline broadcast_tx_* rides (rpc/core.py picks
            # it up via node.ingest)
            from .mempool.ingest import IngestPipeline, ShardedMempool

            self.mempool = ShardedMempool(
                self.proxy_app.mempool, lanes=mp_cfg.lanes,
                ttl_num_blocks=mp_cfg.ttl_num_blocks,
                ttl_duration=mp_cfg.ttl_duration, **mp_common)
            self.ingest = IngestPipeline(
                self.mempool, batch_max=mp_cfg.ingest_batch_max,
                batch_deadline_s=mp_cfg.ingest_batch_deadline_s,
                queue_limit=mp_cfg.ingest_queue_size,
                per_sender_rate=mp_cfg.ingest_per_sender_rate,
                fee_floor=mp_cfg.ingest_fee_floor)
        if config.mempool.wal_dir:
            # NOTE: the WAL is append-only and never pruned on commit, so
            # auto-replaying it at startup would re-admit already-committed
            # txs (double execution for apps without replay protection) —
            # mempool/ingest.replay_mempool_wal stays an EXPLICIT recovery
            # tool, not a boot step (same stance as the reference, which
            # keeps its mempool WAL write-only)
            from .mempool.clist_mempool import init_mempool_wal

            init_mempool_wal(self.mempool, config._rootify(config.mempool.wal_dir))
        self.mempool_reactor = MempoolReactor(
            self.mempool, broadcast=config.mempool.broadcast)

        # -- evidence (node.go:424) -----------------------------------------
        self.evidence_pool = EvidencePool(
            _make_db(backend, dbdir, "evidence"), self.state_store, self.block_store)
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)

        # -- block executor --------------------------------------------------
        self.block_exec = BlockExecutor(
            self.state_store, self.proxy_app.consensus, self.mempool,
            self.evidence_pool, self.block_store, self.event_bus,
            exec_config=config.execution)

        # -- consensus (node.go:465) ----------------------------------------
        wal_path = config.wal_file()
        os.makedirs(os.path.dirname(wal_path), exist_ok=True)
        wal = WAL(wal_path)
        # byzantine e2e hook (reference test/maverick node selected via the
        # e2e manifest): TMTPU_MISBEHAVIORS="3:double-prevote,5:double-prevote"
        # arms the height-keyed misbehavior seam; TMTPU_UNSAFE_PV=1 swaps the
        # double-sign-protected FilePV for a raw MockPV over the same key so
        # the misbehavior can actually equivocate. Test-only, env-gated.
        misbehaviors = {}
        if os.environ.get("TMTPU_MISBEHAVIORS"):
            for part in os.environ["TMTPU_MISBEHAVIORS"].split(","):
                h, _, name = part.partition(":")
                misbehaviors[int(h)] = name
            if (os.environ.get("TMTPU_UNSAFE_PV") == "1"
                    and priv_validator is not None
                    and hasattr(priv_validator, "priv_key")):
                from .types.priv_validator import MockPV

                priv_validator = MockPV(priv_validator.priv_key)

        self.consensus_state = ConsensusState(
            config.consensus, state, self.block_exec, self.block_store,
            evpool=self.evidence_pool, wal=wal)
        self.consensus_state.misbehaviors = misbehaviors
        self.consensus_state.set_event_bus(self.event_bus)
        if priv_validator is not None:
            self.consensus_state.set_priv_validator(priv_validator)
        self.priv_validator = priv_validator
        # crash-recovery guard: a FRESH sign state (height 0) next to a
        # non-empty block store means the last-sign-state file went missing
        # on a validator that has already been part of a chain — every
        # signed height is re-armed for re-signing. FilePV.load already
        # warned; with blocks present, escalate so operators can't miss it.
        lss = getattr(priv_validator, "last_sign_state", None)
        if (lss is not None and lss.height == 0
                and self.block_store.height() > 0):
            logger.warning(
                "priv validator sign state is FRESH (height 0) but the "
                "block store holds heights %d..%d — if this validator "
                "signed any of them, double-sign protection has been "
                "reset; restore %s from backup before relying on it",
                self.block_store.base(), self.block_store.height(),
                getattr(lss, "file_path", "") or "the state file")
        self.mempool.tx_available_callbacks.append(
            self.consensus_state.notify_txs_available)

        # fast sync only makes sense with peers and an existing chain; when
        # state sync is pending, block sync must NOT start at genesis — it
        # enters later via switch_to_fast_sync at the bootstrapped height
        state_sync_pending = (config.statesync.enable
                              and state.last_block_height == 0)
        fast_sync = (config.base.fast_sync and bool(config.p2p.persistent_peers)
                     and not state_sync_pending)
        self.consensus_reactor = ConsensusReactor(
            self.consensus_state, wait_sync=fast_sync or state_sync_pending)

        # -- block sync (node.go:443) ---------------------------------------
        self.fatal_event = asyncio.Event()
        self.fatal_error: Optional[BaseException] = None

        def _on_fatal(exc: BaseException) -> None:
            # deterministic local fault: reference panics; we signal the
            # operator loop (cmd start exits non-zero) and stop accepting
            self.fatal_error = exc
            self.fatal_event.set()

        self.blockchain_reactor = BlockchainReactor(
            state, self.block_exec, self.block_store, fast_sync,
            consensus_reactor=self.consensus_reactor, on_fatal=_on_fatal)
        self._fast_sync = fast_sync

        # -- metrics (node.go:117 MetricsProvider; served on /metrics) ------
        from .libs.metrics import NodeMetrics

        self.metrics = NodeMetrics(config.instrumentation.namespace)
        self.consensus_state.metrics = self.metrics.consensus
        # live-plane series: WAL group-commit fsync stats + the reactor's
        # gossip wakeup/poll and wire-encode-cache counters
        self.consensus_state.wal.metrics = self.metrics.consensus
        self.consensus_reactor.set_metrics(self.metrics.consensus)
        # observability plane: the per-height stage timeline observes
        # tendermint_consensus_stage_seconds{stage} when a height seals,
        # and the (process-global) tracer reports ring saturation
        self.consensus_state.timeline.metrics = self.metrics.consensus
        from .libs.trace import tracer as _tracer

        _tracer.drop_counter = self.metrics.trace_dropped_events_total
        self.mempool.metrics = self.metrics.mempool
        # ingestion-plane lifecycle tracker (libs/txlife.py): hash-sampled
        # per-tx stage stamps from the RPC front door through commit,
        # feeding tendermint_mempool_tx_stage_seconds / _tx_commit_latency
        # and the /tx_timeline route; reached via mempool.txlife by the
        # RPC layer, the gossip reactor, and the consensus hooks
        from .libs.txlife import TxLifecycle

        self.txlife = TxLifecycle()
        self.txlife.metrics = self.metrics.mempool
        self.mempool.txlife = self.txlife
        if self.ingest is not None:
            # admission-control shed counters + intake depth + batched
            # pre-verification series onto the same mempool registry set
            self.ingest.metrics = self.metrics.mempool
        self.block_exec.metrics = self.metrics.state
        from .p2p.conn.mconnection import set_p2p_metrics

        set_p2p_metrics(self.metrics.p2p)
        # verification-plane metrics (crypto/batch.py module hook — covers
        # BatchVerifier everywhere AND the vote micro-batcher) + the
        # fast-sync pipeline's stage set, rebound onto the shared registry
        # so /metrics serves tendermint_crypto_* and tendermint_blocksync_*
        from .crypto.batch import set_crypto_metrics

        set_crypto_metrics(self.metrics.crypto)
        # device-plane phase telemetry (crypto/phases.py): per-segment
        # pack/dispatch/fetch histograms, per-device dispatch counters,
        # and the pipeline-overlap gauge onto the same registry
        from .crypto import phases as _phases

        _phases.set_device_metrics(self.metrics.device)
        self.blockchain_reactor.metrics = self.metrics.blocksync
        # the provider scoreboard counts its bans on the SHARED registry
        # too (it was constructed against the reactor's private set)
        self.blockchain_reactor.scoreboard.bans_counter = \
            self.metrics.blocksync.peer_bans_total
        # robustness plane: breaker state/transitions onto the crypto set,
        # fault-plane fire counts onto their own subsystem
        from .crypto.breaker import set_breaker_metrics
        from .libs.faults import set_fault_metrics

        set_breaker_metrics(self.metrics.crypto)
        set_fault_metrics(self.metrics.faults)
        # crash-recovery plane: surface what this boot repaired and — when
        # a supervisor relaunched us — why (the e2e runner exports
        # TMTPU_RESTART_REASON on supervised relaunches so restart counts
        # live on the restarted node's own /metrics)
        if wal.repairs:
            self.metrics.recovery.wal_repairs_total.inc(wal.repairs)
            self.metrics.recovery.wal_repaired_bytes_total.inc(
                wal.repaired_bytes)
            logger.warning("WAL repair-on-open removed %d torn byte(s); "
                           "recovery continues from the durable prefix",
                           wal.repaired_bytes)
        restart_reason = os.environ.get("TMTPU_RESTART_REASON")
        if restart_reason:
            self.metrics.recovery.restarts_total.labels(restart_reason).inc()
        # resource watermarks (libs/watermark.py): RSS/fds/WAL bytes/
        # txlife ring depth/series cardinality, sampled right before each
        # /metrics render — the slow-leak stream the soak plane's
        # leak-slope SLOs evaluate
        from .libs.watermark import ResourceWatermarks

        self.watermarks = ResourceWatermarks(
            self.metrics.process, txlife=self.txlife,
            wal_paths=[getattr(wal, "path", None),
                       # MempoolWAL opens lazily (init_mempool_wal) and
                       # holds no path attr — resolve through its file
                       lambda: getattr(
                           getattr(getattr(self.mempool, "_wal", None),
                                   "_f", None), "name", None)],
            registry=self.metrics.registry)

        # consensus stall watchdog (config.consensus.stall_watchdog_s > 0,
        # or TMTPU_STALL_WATCHDOG_S for subprocess nets — e2e runner sets
        # it): no committed-height advance for T seconds →
        # consensus_stalled_total + a debugdump bundle under the node home
        self._watchdog = None
        stall_s = float(os.environ.get("TMTPU_STALL_WATCHDOG_S")
                        or config.consensus.stall_watchdog_s)
        if stall_s > 0:
            from .consensus.watchdog import ConsensusWatchdog

            self._watchdog = ConsensusWatchdog(
                self.consensus_state, stall_s,
                metrics=self.metrics.consensus, dump_dir=config.root_dir,
                dump_node=self,
                # block-store height advances during fast-sync AND on every
                # consensus commit — a late joiner block-syncing for longer
                # than stall_s is progress, not a stall
                height_fn=lambda: max(
                    self.block_store.height(),
                    self.consensus_state.state.last_block_height))

        # -- tx/block indexer (node.go:745 createAndStartIndexerService) ----
        self.indexer_service = None
        self.tx_indexer = None
        self.block_indexer = None
        self.event_sink = None
        if config.tx_index.indexer == "kv":
            from .state.txindex import IndexerService, KVBlockIndexer, KVTxIndexer

            self.tx_indexer = KVTxIndexer(_make_db(backend, dbdir, "tx_index"))
            self.block_indexer = KVBlockIndexer(
                _make_db(backend, dbdir, "block_index"))
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus)
        elif config.tx_index.indexer == "psql":
            # SQL event sink (reference state/indexer/sink/psql; sqlite
            # engine here — see state/sink.py). Serves the same indexer
            # seams so /tx and equality tx_search keep working.
            import os as _os

            from .state.sink import BlockSinkAdapter, SQLEventSink
            from .state.txindex import IndexerService

            conn = config.tx_index.psql_conn or _os.path.join(
                dbdir, "events.sqlite")
            self.event_sink = SQLEventSink(conn, genesis.chain_id)
            self.tx_indexer = self.event_sink
            self.block_indexer = BlockSinkAdapter(self.event_sink)
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus)

        # -- state sync (node.go:839) ---------------------------------------
        from .statesync import StateSyncReactor

        self.statesync_reactor = StateSyncReactor(
            self.proxy_app.snapshot, self.proxy_app.query)
        self.statesync_reactor.set_metrics(self.metrics.statesync)
        self._state_sync = state_sync_pending

        # -- transport + switch (node.go:498,567) ---------------------------
        reactors = {
            "MEMPOOL": self.mempool_reactor,
            "BLOCKCHAIN": self.blockchain_reactor,
            "CONSENSUS": self.consensus_reactor,
            "EVIDENCE": self.evidence_reactor,
            "STATESYNC": self.statesync_reactor,
        }
        descs = []
        for r in reactors.values():
            descs.extend(r.get_channels())
        self.node_info = NodeInfo(
            node_id=node_key.id,
            network=genesis.chain_id,
            channels=bytes(d.id for d in descs),
            moniker=config.base.moniker,
            rpc_address=config.rpc.laddr,
        )
        mconn_cfg = MConnConfig(
            send_rate=config.p2p.send_rate, recv_rate=config.p2p.recv_rate,
            max_packet_msg_payload_size=config.p2p.max_packet_msg_payload_size,
            flush_throttle=config.p2p.flush_throttle_timeout)
        # PEX + address book (node.go:872,600; p2p/pex.py)
        if config.p2p.pex:
            from .p2p.pex import AddrBook, PEXReactor

            # the book shares blocksync's peer-score ledger: a provider
            # blocksync severe-banned must not keep being redialed (or
            # re-advertised) by PEX, and mark_bad strikes land where the
            # sync planes already look
            self.addr_book = AddrBook(
                config._rootify(config.p2p.addr_book_file),
                strict=config.p2p.addr_book_strict,
                scoreboard=self.blockchain_reactor.scoreboard)
            self.addr_book.add_our_address(node_key.id)
            # seed the book from config.p2p.seeds (node.go:600 createAddrBook)
            for addr in parse_peer_list(config.p2p.seeds):
                self.addr_book.add_address(addr)
            self.pex_reactor = PEXReactor(
                self.addr_book,
                target_outbound=config.p2p.max_num_outbound_peers,
                seed_mode=config.p2p.seed_mode)
            reactors["PEX"] = self.pex_reactor
            descs.extend(self.pex_reactor.get_channels())
        else:
            self.addr_book = None
            self.pex_reactor = None

        from .p2p.trust import TrustMetricStore

        self.trust_store = TrustMetricStore(
            db=_make_db(backend, dbdir, "trust_history"))
        self.transport = TCPTransport(node_key, self.node_info, descs, mconn_cfg)
        self.switch = Switch(node_key.id, transport=self.transport,
                             trust_store=self.trust_store)
        for name, r in reactors.items():
            self.switch.add_reactor(name, r)

        # -- light-client serving plane (light/serve.py) ----------------------
        self.light_serve = None
        if config.lightserve.enable:
            from .light.serve import LightServePlane

            self.light_serve = LightServePlane(
                block_store=self.block_store, state_store=self.state_store,
                chain_id=genesis.chain_id, config=config.lightserve,
                metrics=self.metrics.lightserve)

        # -- RPC --------------------------------------------------------------
        self.rpc_server = None
        if config.rpc.laddr:
            from .rpc.server import RPCServer

            self.rpc_server = RPCServer(self)
            # per-endpoint latency/outcome, in-flight, ws-subscriber, and
            # size series onto the shared registry
            self.rpc_server.metrics = self.metrics.rpc

        self.listen_addr = None
        self._started = False

    # -- construction helpers ------------------------------------------------

    @classmethod
    def default(cls, config: Config, app: Optional[Application] = None) -> "Node":
        """(node.go:100 DefaultNewNode) load node key / FilePV / genesis —
        or, with priv_validator_laddr set, listen for a remote signer
        (node.go:753 createAndStartPrivValidatorSocketClient)."""
        node_key = NodeKey.load_or_gen(config.node_key_file())
        genesis = GenesisDoc.from_file(config.genesis_file())
        pv: Optional[PrivValidator]
        if config.base.priv_validator_laddr:
            from .privval.signer import SignerClient, SignerListenerEndpoint

            addr = config.base.priv_validator_laddr.split("://", 1)[-1]
            host, _, port = addr.rpartition(":")
            pinned = None
            if config.base.priv_validator_signer_key:
                try:
                    pinned = bytes.fromhex(config.base.priv_validator_signer_key)
                except ValueError as e:
                    raise ValueError(
                        "priv_validator_signer_key is not valid hex") from e
                if len(pinned) != 32:
                    raise ValueError(
                        f"priv_validator_signer_key must be a 32-byte ed25519 "
                        f"pubkey, got {len(pinned)} bytes")
            endpoint = SignerListenerEndpoint(host or "127.0.0.1", int(port),
                                              conn_key=node_key.priv_key,
                                              expected_signer_key=pinned)
            endpoint.wait_for_signer()
            pv = SignerClient(endpoint, genesis.chain_id)
            pv.get_pub_key()  # fail fast if the signer is broken
        else:
            key_file = config.priv_validator_key_file()
            state_file = config.priv_validator_state_file()
            if os.path.exists(key_file):
                pv = FilePV.load(key_file, state_file)
            else:
                pv = FilePV.generate(key_file, state_file)
                pv.save()
        return cls(config, pv, node_key, genesis, app=app)

    # -- lifecycle (node.go:941 OnStart) -------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        if self.indexer_service is not None:
            await self.indexer_service.start()
        if self.config.instrumentation.prometheus:
            await self._start_metrics_server()
        if self.rpc_server is not None:
            await self.rpc_server.start(self.config.rpc.laddr)
        await self.switch.start()
        host, port = _parse_laddr(self.config.p2p.laddr)
        self.listen_addr = await self.switch.listen(host, port)
        if self._state_sync:
            self._statesync_task = asyncio.create_task(self._run_state_sync())
        elif not self._fast_sync:
            # WAL catchup for the in-flight height BEFORE the state machine
            # runs (consensus/state.go:299 OnStart → replay.go:93): replays
            # our own signed msgs so restart doesn't trip double-sign
            # protection by re-signing an already-signed proposal/vote.
            from .consensus.replay import catchup_replay

            replayed = catchup_replay(self.consensus_state,
                                      self.consensus_state.rs.height)
            self.metrics.recovery.wal_records_replayed.set(replayed)
            await self.consensus_state.start()
        # (fast-sync case: Switch.start() already started the reactor)
        if self._watchdog is not None:
            await self._watchdog.start()
        if self.config.p2p.persistent_peers:
            peers = parse_peer_list(self.config.p2p.persistent_peers)
            self.switch.dial_peers_async(peers, persistent=True)
        import time as _time

        self.metrics.recovery.recovery_duration_seconds.set(
            _time.monotonic() - self._boot_t0)
        logger.info("node %s started: p2p=%s rpc=%s", self.node_key.id[:8],
                    self.listen_addr, self.config.rpc.laddr or "off")

    async def _start_metrics_server(self) -> None:
        """(node.go:962) /metrics in Prometheus text format."""
        from aiohttp import web

        async def metrics(request):
            self.metrics.p2p.peers.set(len(self.switch.peers))
            try:
                self.watermarks.sample()
            except Exception:
                pass
            return web.Response(text=self.metrics.registry.render(),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/metrics", metrics)
        self._metrics_runner = web.AppRunner(app, access_log=None)
        await self._metrics_runner.setup()
        addr = self.config.instrumentation.prometheus_listen_addr
        addr = addr.split("://", 1)[-1]  # accept tcp://host:port like laddrs
        host, _, port = addr.rpartition(":")
        site = web.TCPSite(self._metrics_runner, host or "127.0.0.1", int(port))
        await site.start()
        self.metrics_port = (self._metrics_runner.addresses[0][1]
                             if self._metrics_runner.addresses else int(port))

    async def _run_state_sync(self) -> None:
        """(node.go:648 startStateSync) snapshot restore → bootstrap stores →
        hand off to fast sync. A failed restore (no viable snapshots, every
        provider lying/banned) is NOT fatal: a fresh node can always replay
        the chain, so it degrades to fast sync from its current (genesis)
        state instead of wedging the process."""
        from .light.client import TrustOptions
        from .rpc.client import HTTPClient
        from .statesync import LightClientStateProvider

        cfg = self.config.statesync
        try:
            clients = [HTTPClient(s) for s in cfg.rpc_servers]
            # one peer-score ledger across the whole bootstrap: lying chunk
            # servers (syncer) and diverging light-client witnesses
            # (provider) land on the same peer_bans_total series
            scoreboard = self.statesync_reactor.make_scoreboard(
                ban_threshold=cfg.peer_ban_threshold)
            provider = LightClientStateProvider(
                self.genesis.chain_id, self.genesis, clients,
                TrustOptions(cfg.trust_period, cfg.trust_height,
                             bytes.fromhex(cfg.trust_hash)),
                scoreboard=scoreboard)
            state, commit = await self.statesync_reactor.sync(
                provider, cfg.discovery_time,
                chunk_fetchers=int(
                    os.environ.get("TMTPU_STATESYNC_CHUNK_FETCHERS")
                    or cfg.chunk_fetchers),
                chunk_timeout=float(
                    os.environ.get("TMTPU_STATESYNC_CHUNK_TIMEOUT")
                    or cfg.chunk_request_timeout),
                discovery_rounds=cfg.discovery_attempts,
                scoreboard=scoreboard)
            self.state_store.bootstrap(state)
            self.block_store.save_seen_commit(state.last_block_height, commit)
            # consensus catches up via the fast-sync handoff
            # (switch_to_consensus → reconstruct_last_commit + update_to_state)
            logger.info("state sync complete at height %d; entering fast sync",
                        state.last_block_height)
            await self.blockchain_reactor.switch_to_fast_sync(state)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # replaying from genesis is only sound against a PRISTINE app:
            # a restore that already landed (then failed the trusted-hash
            # check, or whose provider died afterwards) left the app at the
            # snapshot height, and executing block 1 onto it would diverge
            from .abci import types as abci_types

            try:
                info = self.proxy_app.query.info(abci_types.RequestInfo())
                pristine = info.last_block_height == 0
            except Exception:
                pristine = False
            if not pristine:
                logger.critical(
                    "state sync failed (%s) after the app was mutated; "
                    "cannot fall back to fast sync", e)
                self.fatal_error = e
                self.fatal_event.set()
                return
            logger.critical(
                "state sync failed (%s); falling back to fast sync from "
                "height %d", e, self.blockchain_reactor.state.last_block_height)
            self.metrics.statesync.fallbacks_total.inc()
            try:
                await self.blockchain_reactor.switch_to_fast_sync(
                    self.blockchain_reactor.state)
            except Exception as e2:  # the fallback itself dying IS fatal
                logger.critical("fast-sync fallback failed: %s", e2)
                self.fatal_error = e2
                self.fatal_event.set()

    async def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        task = getattr(self, "_statesync_task", None)
        if task is not None and not task.done():
            task.cancel()
        if self._watchdog is not None:
            await self._watchdog.stop()
        await self.consensus_state.stop()
        if self.indexer_service is not None:
            await self.indexer_service.stop()
        await self.switch.stop()
        if self.rpc_server is not None:
            await self.rpc_server.stop()
        if self.light_serve is not None:
            # fail queued verifies with an explicit shed, cancel the timer
            self.light_serve.stop()
        if self.ingest is not None:
            # settle any in-flight micro-batch so no submit future strands
            await self.ingest.stop()
        runner = getattr(self, "_metrics_runner", None)
        if runner is not None:
            await runner.cleanup()
        wal = getattr(self.mempool, "_wal", None)
        if wal is not None:
            wal.close()
        self.proxy_app.stop()


def _parse_laddr(laddr: str):
    """tcp://host:port -> (host, port)"""
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)
