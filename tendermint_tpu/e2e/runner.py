"""E2E testnet runner (reference test/e2e/runner/main.go stages:
setup → start → load → perturb → wait → test → stop).

Drives subprocess nodes (python -m tendermint_tpu.cmd start) generated from
a Manifest. Perturbations follow test/e2e/runner/perturb.go:28-66: kill
(SIGKILL + relaunch), restart (SIGTERM + relaunch), pause (SIGSTOP/SIGCONT),
disconnect (approximated with a long SIGSTOP so peers drop and re-dial —
subprocess nets have no network namespace to unplug).

Invariants after the run (reference test/e2e/tests/): all nodes reach a
common height, app hashes agree at sampled heights, txs injected during the
load stage are queryable everywhere, and byzantine double-votes surface as
committed DuplicateVoteEvidence.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from ..config import CONFIG_DIR, DATA_DIR, Config
from ..libs.supervisor import (RestartSupervisor, policy_from_manifest,
                               write_crashloop_bundle)
from .manifest import Manifest, NodeManifest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _have_aiohttp() -> bool:
    """The node's /metrics server needs aiohttp; slim containers without it
    must still run e2e nets (just without the fleet scrape plane)."""
    import importlib.util

    return importlib.util.find_spec("aiohttp") is not None


def _fleet_scrape_mod():
    """Import tools/fleet_scrape.py (stdlib-only, lives outside the
    package)."""
    from ..libs.toolbox import load_tool

    return load_tool("fleet_scrape")


class E2EError(Exception):
    pass


class Runner:
    def __init__(self, manifest: Manifest, root: str, base_port: int = 29000):
        self.m = manifest
        self.root = root
        self.base_port = base_port
        self.procs: Dict[str, subprocess.Popen] = {}
        self.signers: Dict[str, subprocess.Popen] = {}
        self.configs: Dict[str, Config] = {}
        self.node_ids: Dict[str, str] = {}
        self.loaded_txs: List[bytes] = []
        self.departed: set = set()    # clean stop_at leaves (not failures)
        #: crash-recovery plane: one supervisor per restart_policy !=
        #: "never" node; poll_restarts() consults them whenever a wait
        #: loop notices a dead process
        self.supervisors: Dict[str, RestartSupervisor] = {
            nm.name: RestartSupervisor(policy_from_manifest(nm), nm.name)
            for nm in manifest.nodes if nm.restart_policy != "never"}
        self.crashloop_bundles: Dict[str, str] = {}
        #: nodes launched at least once — a fail_point arms ONLY the first
        #: launch, whoever relaunches (supervisor, perturbation, joiner)
        self._launched: set = set()
        #: name -> join-to-caught-up seconds for late joiners (the churn
        #: metric: launch → height >= the net's height at launch time)
        self.join_stats: Dict[str, float] = {}
        self._join_marks: Dict[str, tuple] = {}
        self._fleet = None            # FleetScraper while the net runs
        self.fleet_rollup: Optional[dict] = None
        self._log = open(os.path.join(root, "runner.log"), "w") \
            if os.path.isdir(root) else None

    # -- ports ---------------------------------------------------------------

    def _ports(self, i: int):
        base = self.base_port + 4 * i
        return base, base + 1, base + 2  # p2p, rpc, privval (+3 = metrics)

    def _rpc_port(self, name: str) -> int:
        idx = [n.name for n in self.m.nodes].index(name)
        return self._ports(idx)[1]

    def _metrics_port(self, name: str) -> int:
        idx = [n.name for n in self.m.nodes].index(name)
        return self.base_port + 4 * idx + 3

    # -- stages --------------------------------------------------------------

    def setup(self) -> None:
        """Generate per-node homes, one shared genesis, manifest knobs
        applied to each config."""
        from ..p2p import NodeKey
        from ..privval.file_pv import FilePV
        from ..types import GenesisDoc, GenesisValidator

        os.makedirs(self.root, exist_ok=True)
        pvs: Dict[str, FilePV] = {}
        for i, nm in enumerate(self.m.nodes):
            home = os.path.join(self.root, nm.name)
            p2p, rpc, pvp = self._ports(i)
            cfg = Config(root_dir=home)
            cfg.base.chain_id = self.m.chain_id
            cfg.base.moniker = nm.name
            cfg.base.proxy_app = "kvstore-snapshot"
            cfg.base.fast_sync = nm.fast_sync
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc}"
            cfg.mempool.version = nm.mempool_version
            if _have_aiohttp():
                # fleet observability: every node serves /metrics on the
                # 4th port of its block so the runner's fleet scraper can
                # roll up cluster-truth series during the run
                cfg.instrumentation.prometheus = True
                cfg.instrumentation.prometheus_listen_addr = (
                    f"tcp://127.0.0.1:{self._metrics_port(nm.name)}")
            if nm.privval == "tcp":
                cfg.base.priv_validator_laddr = f"tcp://127.0.0.1:{pvp}"
            if nm.state_sync:
                cfg.statesync.enable = True
                cfg.statesync.discovery_time = 3.0
                # adversarial nets: chunk peers may be lying — time out and
                # strike fast so a bounded run reaches ban/fallback verdicts
                cfg.statesync.chunk_request_timeout = 5.0
                cfg.statesync.peer_ban_threshold = 2
            os.makedirs(os.path.join(home, CONFIG_DIR), exist_ok=True)
            os.makedirs(os.path.join(home, DATA_DIR), exist_ok=True)
            pv = FilePV.generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
            pv.save()
            pvs[nm.name] = pv
            nk = NodeKey.load_or_gen(cfg.node_key_file())
            self.node_ids[nm.name] = nk.id
            self.configs[nm.name] = cfg

        powers = self.m.validators or {
            nm.name: 10 for nm in self.m.nodes if nm.mode == "validator"}
        genesis = GenesisDoc(
            chain_id=self.m.chain_id,
            genesis_time_ns=time.time_ns(),
            initial_height=self.m.initial_height,
            validators=[GenesisValidator(pvs[name].get_pub_key(), power)
                        for name, power in powers.items()
                        if name in pvs],
        )
        for i, nm in enumerate(self.m.nodes):
            cfg = self.configs[nm.name]
            cfg.p2p.persistent_peers = ",".join(
                self._peer_addr(other) for other in self._peers_of(nm))
            if self.m.topology == "seed" and not nm.seed_node:
                cfg.p2p.seeds = ",".join(
                    self._peer_addr(o) for o in self.m.nodes if o.seed_node)
            if nm.seed_node:
                cfg.p2p.seed_mode = True
            genesis.save_as(cfg.genesis_file())
            cfg.save()

    def _peer_addr(self, nm: NodeManifest) -> str:
        idx = [n.name for n in self.m.nodes].index(nm.name)
        return f"{self.node_ids[nm.name]}@127.0.0.1:{self._ports(idx)[0]}"

    def _peers_of(self, nm: NodeManifest) -> List[NodeManifest]:
        """Persistent peers per the manifest topology: every other node
        (full_mesh), graph neighbors (sparse — the SAME seeded ring+chords
        graph p2p.inproc.sparse_edges builds for in-proc nets), or nobody
        (seed — discovery fills the peer set via PEX)."""
        if self.m.topology == "seed":
            return []
        others = [o for o in self.m.nodes if o.name != nm.name]
        if self.m.topology == "full_mesh":
            return others
        from ..p2p.inproc import sparse_edges

        edges = sparse_edges([n.name for n in self.m.nodes],
                             degree=self.m.sparse_degree,
                             seed=self.m.topology_seed)
        mine = {b if a == nm.name else a
                for a, b in edges if nm.name in (a, b)}
        return [o for o in others if o.name in mine]

    def _env(self, nm: NodeManifest, first_launch: bool = True,
             restart_reason: str = "") -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # all subprocess nodes share the repo's warm XLA compile cache
        env.setdefault("TMTPU_JAX_CACHE", os.path.join(REPO, ".jax_cache"))
        if nm.misbehaviors:
            env["TMTPU_MISBEHAVIORS"] = ",".join(
                f"{h}:{b}" for h, b in sorted(nm.misbehaviors.items()))
            env["TMTPU_UNSAFE_PV"] = "1"
        if nm.faults:
            # arm the node's fault plane (libs/faults.py reads these at
            # import, so the subprocess starts with the sites live)
            env["TMTPU_FAULTS"] = nm.faults
            env["TMTPU_FAULTS_SEED"] = str(nm.faults_seed)
        if nm.fail_point and first_launch:
            # one-shot: the FIRST process dies at the boundary; supervised
            # relaunches drop the arming so recovery can be observed
            env["TMTPU_FAIL_POINT"] = nm.fail_point
        if restart_reason:
            # the restarted node exports restarts_total{reason} on its own
            # /metrics (libs/metrics.py RecoveryMetrics, wired in node.py)
            env["TMTPU_RESTART_REASON"] = restart_reason
        # stall watchdog: an e2e node that silently stops committing should
        # leave a debugdump bundle behind, not just a hung run
        env.setdefault("TMTPU_STALL_WATCHDOG_S", "60")
        # cluster observability: node traces carry the manifest name, and a
        # watchdog debugdump snapshots the runner's fleet rollup (the
        # scraper keeps this file fresh while the net runs)
        env["TMTPU_NODE_ID"] = nm.name
        env["TMTPU_FLEET_JSON"] = os.path.join(self.root, "fleet.json")
        return env

    def _launch(self, nm: NodeManifest, restart_reason: str = "") -> None:
        cfg = self.configs[nm.name]
        # the one-shot fail_point arming is derived HERE, not passed by
        # callers: perturbation relaunches and supervised restarts alike
        # must drop it or the node dies at the boundary forever
        env = self._env(nm, first_launch=nm.name not in self._launched,
                        restart_reason=restart_reason)
        self._launched.add(nm.name)
        sup = self.supervisors.get(nm.name)
        if sup is not None:
            sup.on_launch()
        if nm.privval == "tcp" and nm.name not in self.signers:
            pvp = cfg.base.priv_validator_laddr.rpartition(":")[-1]
            self.signers[nm.name] = subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.cmd", "signer",
                 "--key-file", cfg.priv_validator_key_file(),
                 "--state-file", cfg.priv_validator_state_file(),
                 "--chain-id", self.m.chain_id,
                 "--addr", f"127.0.0.1:{pvp}"],
                env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        log = open(os.path.join(self.root, f"{nm.name}.log"), "a")
        self.procs[nm.name] = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cmd",
             "--home", cfg.root_dir, "start", "--log-level",
             os.environ.get("TMTPU_E2E_LOG_LEVEL", "warning")],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT)

    def start(self) -> None:
        """Launch genesis nodes; late joiners wait for their start_at."""
        for nm in self.m.nodes:
            if nm.start_at == 0:
                self._launch(nm)
        self.wait_for_height(max(2, self.m.initial_height + 1),
                             nodes=[n.name for n in self.m.nodes
                                    if n.start_at == 0])

    def start_late_joiners(self) -> None:
        for nm in self.m.nodes:
            if nm.start_at == 0 or nm.name in self.procs:
                continue
            self.wait_for_height(nm.start_at)
            if nm.state_sync:
                self._point_state_sync(nm)
            # join-to-caught-up: the clock starts at launch, the target is
            # the net's height NOW (what "caught up" meant when it joined)
            self._join_marks[nm.name] = (time.time(), max(1, self.max_height()))
            self._launch(nm)
            if self._fleet is not None:
                self._fleet.add_endpoint(
                    nm.name,
                    f"http://127.0.0.1:{self._metrics_port(nm.name)}/metrics")

    def measure_join_catchup(self, timeout: float = 180.0) -> Dict[str, float]:
        """Block until each launched late joiner reaches the height the net
        held when it was launched; records seconds into join_stats."""
        for name, (t0, target) in list(self._join_marks.items()):
            deadline = time.time() + timeout
            while time.time() < deadline:
                self.poll_restarts()
                if self.height(name) >= target:
                    self.join_stats[name] = round(time.time() - t0, 3)
                    break
                time.sleep(0.5)
            else:
                raise E2EError(
                    f"joiner {name} never caught up to h={target}")
            del self._join_marks[name]
        return self.join_stats

    def apply_churn_stops(self) -> None:
        """The leave half of the churn schedule: nodes with stop_at get a
        clean SIGTERM once the net reaches that height and are excluded
        from post-run invariants — a scheduled departure is not a dead
        node. Processed in stop_at order so multi-leave schedules play out
        deterministically."""
        for nm in sorted((n for n in self.m.nodes if n.stop_at),
                         key=lambda n: (n.stop_at, n.name)):
            proc = self.procs.get(nm.name)
            if proc is None:
                continue
            self.wait_for_height(nm.stop_at)
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            self.procs.pop(nm.name, None)
            self.departed.add(nm.name)
            if self._fleet is not None:
                self._fleet.remove_endpoint(nm.name)

    def poll_restarts(self) -> None:
        """Crash-recovery supervision: relaunch any supervised node whose
        process died (non-clean exit, not a scheduled departure) after its
        policy's backoff; on crash-loop give-up, write the debugdump
        bundle and leave the node down (invariant checks will then fail
        loudly — a crash loop IS a failed run). Called from every wait
        loop so supervision needs no extra thread."""
        by_name = {nm.name: nm for nm in self.m.nodes}
        for name, sup in self.supervisors.items():
            proc = self.procs.get(name)
            if proc is None or name in self.departed:
                continue
            rc = proc.poll()
            if rc is None:
                continue  # still running
            delay = sup.on_exit(rc)
            if delay is None:
                if sup.gave_up and name not in self.crashloop_bundles:
                    self.crashloop_bundles[name] = write_crashloop_bundle(
                        self.root, sup,
                        extras={"manifest_node": name,
                                "home": self.configs[name].root_dir},
                        log_path=os.path.join(self.root, f"{name}.log"))
                    self._note(f"supervisor gave up on {name} "
                               f"(crash loop); bundle at "
                               f"{self.crashloop_bundles[name]}")
                # staying down (clean exit or give-up): drop the carcass so
                # the next poll doesn't re-record the same exit forever
                self.procs.pop(name, None)
                continue
            self._note(f"supervisor restarting {name} (rc={rc}, "
                       f"restart #{sup.restarts}) after {delay:.2f}s")
            time.sleep(delay)
            self._launch(by_name[name],
                         restart_reason=sup.history[-1].reason)

    def _note(self, msg: str) -> None:
        if self._log:
            self._log.write(msg + "\n")
            self._log.flush()

    def _point_state_sync(self, nm: NodeManifest) -> None:
        """Fill rpc_servers + trust root from the live net just before the
        joiner starts (reference test/e2e/runner/setup.go does the same with
        a light-client trust height)."""
        donors = [o for o in self.m.nodes
                  if o.name in self.procs and not o.state_sync][:2]
        if len(donors) < 2:
            donors = donors * 2
        h = self.rpc(donors[0].name, "status")["sync_info"]["latest_block_height"]
        trust_h = max(1, int(h) - 2)
        commit = self.rpc(donors[0].name, f"commit?height={trust_h}")
        trust_hash = commit["signed_header"]["commit"]["block_id"]["hash"]
        cfg = self.configs[nm.name]
        cfg.statesync.rpc_servers = [
            f"http://127.0.0.1:{self._rpc_port(d.name)}" for d in donors]
        cfg.statesync.trust_height = trust_h
        cfg.statesync.trust_hash = trust_hash
        cfg.save()

    def load(self, n_txs: Optional[int] = None) -> None:
        """Inject txs via broadcast_tx_sync round-robin over live nodes."""
        names = [n.name for n in self.m.nodes if n.name in self.procs]
        n_txs = n_txs if n_txs is not None else max(4, self.m.load_tx_rate * 2)
        for i in range(n_txs):
            tx = f"e2e{len(self.loaded_txs)}=v{i}".encode()
            name = names[i % len(names)]
            try:
                self.rpc_post(name, "broadcast_tx_sync",
                              {"tx": base64.b64encode(tx).decode()})
                self.loaded_txs.append(tx)
            except Exception:
                pass  # a node may be mid-perturbation; coverage, not load
            time.sleep(1.0 / max(1, self.m.load_tx_rate))

    def perturb(self) -> None:
        """Apply each node's perturbations sequentially
        (test/e2e/runner/perturb.go)."""
        for nm in self.m.nodes:
            for p in nm.perturb:
                proc = self.procs.get(nm.name)
                if proc is None:
                    continue
                if p == "kill":
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()
                    time.sleep(2.0)
                    self._launch(nm)
                elif p == "restart":
                    proc.send_signal(signal.SIGTERM)
                    try:
                        proc.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    self._launch(nm)
                elif p == "pause":
                    proc.send_signal(signal.SIGSTOP)
                    time.sleep(5.0)
                    proc.send_signal(signal.SIGCONT)
                elif p == "disconnect":
                    # no netns for subprocesses: a long stop makes every peer
                    # drop the conn (ping timeout) and re-dial on CONT
                    proc.send_signal(signal.SIGSTOP)
                    time.sleep(12.0)
                    proc.send_signal(signal.SIGCONT)
                time.sleep(2.0)

    def wait(self, blocks: Optional[int] = None) -> None:
        """Let the net advance `blocks` past the current max height."""
        target = self.max_height() + (blocks or self.m.wait_blocks)
        self.wait_for_height(target)

    # -- fleet metrics (tools/fleet_scrape.py) -------------------------------

    def start_fleet_scrape(self, interval_s: float = 2.0) -> None:
        """Scrape every launched node's /metrics on an interval; the rollup
        JSON (root/fleet.json) stays fresh for debugdump bundles and is
        summarized into self.fleet_rollup at stop."""
        if self._fleet is not None or not _have_aiohttp():
            return
        endpoints = {
            name: f"http://127.0.0.1:{self._metrics_port(name)}/metrics"
            for name in self.procs}
        if not endpoints:
            return
        mod = _fleet_scrape_mod()
        self._fleet = mod.FleetScraper(
            endpoints, interval_s=interval_s,
            out_path=os.path.join(self.root, "fleet.json")).start()

    def stop_fleet_scrape(self) -> Optional[dict]:
        if self._fleet is None:
            return None
        # stop()'s final sweep already refreshed out_path (root/fleet.json)
        self.fleet_rollup = self._fleet.stop()
        self._fleet = None
        return self.fleet_rollup

    def stop(self) -> None:
        self.stop_fleet_scrape()
        for proc in list(self.procs.values()) + list(self.signers.values()):
            try:
                proc.send_signal(signal.SIGTERM)
            except Exception:
                pass
        deadline = time.time() + 15
        for proc in list(self.procs.values()) + list(self.signers.values()):
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                proc.kill()
        if self._log:
            self._log.close()

    # -- RPC helpers ---------------------------------------------------------

    def rpc(self, name: str, path: str, timeout: float = 5.0):
        url = f"http://127.0.0.1:{self._rpc_port(name)}/{path}"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            doc = json.load(r)
        if "error" in doc and doc["error"]:
            raise E2EError(f"{name} /{path}: {doc['error']}")
        return doc["result"]

    def rpc_post(self, name: str, method: str, params: dict,
                 timeout: float = 10.0):
        body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                           "params": params}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self._rpc_port(name)}/", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            doc = json.load(r)
        if "error" in doc and doc["error"]:
            raise E2EError(f"{name} {method}: {doc['error']}")
        return doc["result"]

    def metric_value(self, name: str, series_prefix: str,
                     timeout: float = 5.0) -> float:
        """Sum a node's /metrics series whose line starts with
        `series_prefix` (label sets summed) — how e2e assertions read ban /
        fault / retry counters off a live node. 0.0 when the series is
        absent or the endpoint is down."""
        url = f"http://127.0.0.1:{self._metrics_port(name)}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                text = r.read().decode()
        except Exception:
            return 0.0
        total = 0.0
        for line in text.splitlines():
            if not line.startswith(series_prefix) or line.startswith("#"):
                continue
            rest = line[len(series_prefix):]
            if rest and rest[0] not in "{ ":
                continue  # longer metric name sharing the prefix
            try:
                total += float(line.rsplit(None, 1)[-1])
            except ValueError:
                continue
        return total

    def height(self, name: str) -> int:
        try:
            return int(self.rpc(name, "status")
                       ["sync_info"]["latest_block_height"])
        except Exception:
            return -1

    def max_height(self) -> int:
        return max([self.height(n) for n in self.procs] or [0])

    def wait_all_alive(self, timeout: float = 180.0) -> None:
        """Block until every launched node answers /status — node startup
        (python + jax import + WAL replay) can take a minute under CI load,
        and invariants checked against a still-booting node read as a dead
        net."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.poll_restarts()
            down = [n for n in self.procs if self.height(n) < 0]
            if not down:
                return
            for n in down:  # an unsupervised crashed process never answers
                if (self.procs[n].poll() is not None
                        and n not in self.supervisors):
                    raise E2EError(
                        f"node {n} exited rc={self.procs[n].returncode}")
            time.sleep(1.0)
        raise E2EError(f"nodes never became reachable: {down}")

    def wait_for_height(self, h: int, nodes: Optional[List[str]] = None,
                        timeout: float = 180.0) -> None:
        names = nodes or list(self.procs)
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.poll_restarts()
            if any(self.height(n) >= h for n in names):
                return
            time.sleep(1.0)
        raise E2EError(
            f"height {h} not reached in {timeout}s: "
            f"{ {n: self.height(n) for n in names} }")

    # -- invariants (reference test/e2e/tests/) ------------------------------

    def check_invariants(self) -> None:
        self.check_heights_agree()
        self.check_app_hashes()
        self.check_txs_everywhere()

    def check_heights_agree(self, spread: int = 3) -> None:
        hs = {n: self.height(n) for n in self.procs}
        if min(hs.values()) < 1:
            raise E2EError(f"dead node: {hs}")
        if max(hs.values()) - min(hs.values()) > spread:
            # stragglers get a grace period to catch up
            target = max(hs.values())
            deadline = time.time() + 60
            while time.time() < deadline:
                hs = {n: self.height(n) for n in self.procs}
                if min(hs.values()) >= target - spread:
                    return
                time.sleep(1.0)
            raise E2EError(f"heights diverged: {hs}")

    def check_app_hashes(self) -> None:
        """All nodes report the same app hash at a sampled common height."""
        h = min(self.height(n) for n in self.procs) - 1
        if h < 2:
            raise E2EError("chain too short for app-hash check")
        hashes = {}
        for n in self.procs:
            doc = self.rpc(n, f"commit?height={h}")
            hashes[n] = doc["signed_header"]["header"]["app_hash"]
        if len(set(hashes.values())) != 1:
            raise E2EError(f"app hash mismatch at {h}: {hashes}")

    def check_txs_everywhere(self) -> None:
        """Every loaded tx's key is queryable on every node."""
        if not self.loaded_txs:
            return
        sample = self.loaded_txs[:: max(1, len(self.loaded_txs) // 4)]
        for n in self.procs:
            for tx in sample:
                key = tx.split(b"=", 1)[0]
                q = self.rpc(
                    n, f'abci_query?path=%22%22&data={key.hex()}', timeout=10)
                value = q["response"].get("value")
                if not value:
                    raise E2EError(f"tx key {key!r} missing on {n}")

    def check_evidence_committed(self, timeout: float = 90.0) -> None:
        """A byzantine manifest must produce committed DuplicateVoteEvidence
        (reference evidence pool -> block evidence path)."""
        deadline = time.time() + timeout
        names = list(self.procs)
        while time.time() < deadline:
            top = self.max_height()
            for h in range(2, top):
                for n in names:
                    try:
                        blk = self.rpc(n, f"block?height={h}")
                    except Exception:
                        continue
                    ev = blk["block"].get("evidence") or []
                    if ev:
                        return
            time.sleep(2.0)
        raise E2EError("no evidence committed within deadline")

    # -- one-call orchestration ----------------------------------------------

    def run(self) -> None:
        """setup → start → load → late joiners (join-to-caught-up timed) →
        perturb → load → churn leaves (stop_at) → wait → invariants →
        stop. Raises E2EError on any failed invariant."""
        self.setup()
        try:
            self.start()
            self.start_fleet_scrape()
            self.load()
            self.start_late_joiners()
            self.wait_all_alive()
            self.measure_join_catchup()
            self.perturb()
            self.load()
            self.apply_churn_stops()
            self.wait_all_alive()
            self.wait()
            self.check_invariants()
            if any(nm.misbehaviors for nm in self.m.nodes):
                self.check_evidence_committed()
        finally:
            self.stop()
