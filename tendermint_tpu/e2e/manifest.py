"""E2E testnet manifest (reference test/e2e/pkg/manifest.go:11).

TOML shape:

    chain_id = "e2e-net"
    initial_height = 1
    load_tx_rate = 2            # txs/sec during the load stage
    wait_blocks = 6             # blocks to wait after perturbations
    topology = "full_mesh"      # full_mesh | sparse | seed
    sparse_degree = 3           # sparse: ~persistent peers per node
    topology_seed = 0           # sparse: chord-graph seed

    [validators]                # name -> voting power (defaults: all 4 @ 10)
    validator0 = 10

    [node.validator0]
    mode = "validator"          # validator | full
    mempool_version = "v2"      # v0 | v2 (v1 = legacy alias for v2)
    fast_sync = true
    state_sync = false
    privval = "file"            # file | tcp (remote signer over SecretConn)
    start_at = 0                # join the net after this height (0 = launch)
    stop_at = 0                 # LEAVE the net at this height (0 = never):
                                # a clean SIGTERM departure, excluded from
                                # post-run invariants — the churn schedule
    seed_node = false           # topology="seed": this node is the
                                # discovery entry everyone else learns
                                # peers from (PEX), not a persistent peer
    perturb = ["kill"]          # kill | pause | restart | disconnect
    fail_point = "wal.after_fsync"  # die ONCE at this durability boundary
                                # (libs/fail.py KNOWN_FAIL_POINTS; needs
                                # restart_policy = "on-failure")
    restart_policy = "never"    # never | on-failure (supervised relaunch
                                # with bounded exponential backoff)
    max_restarts = 3            # consecutive fast crashes before give-up
                                # (crash-loop debugdump bundle written)
    backoff_s = 1.0             # base backoff; doubles per consecutive crash
    [node.validator0.misbehaviors]
    3 = "double-prevote"        # height -> misbehavior (maverick hooks)

Topology semantics: ``full_mesh`` lists every other node as a persistent
peer (the old behavior, and the default). ``sparse`` wires the
deterministic ring+chords graph from p2p.inproc.sparse_edges — each node
persistent-dials only its graph neighbors, so gossip must relay. ``seed``
gives non-seed nodes ONLY config.p2p.seeds (the seed_node entries) and no
persistent peers: the net assembles itself through PEX discovery.

Perturbation semantics: kill/pause/restart match the reference's
(test/e2e/runner/perturb.go:28-66). ``disconnect`` is an APPROXIMATION —
subprocess nets have no network namespace to unplug, so it is a long
SIGSTOP: peers drop the frozen node on ping timeout and re-dial after
SIGCONT. One-way partitions and asymmetric connectivity are NOT
representable over subprocess TCP; the reference uses docker network
disconnect (perturb.go:48) for true partitions. The IN-PROC plane does
represent them (p2p.inproc LINK_PROFILES / partition_oneway), and the
manifest mirrors the profile grammar so the same degraded-network intent
validates in both worlds:

    link_profile = "wan"        # "" | wan | gray | asym — named profile
                                # from p2p.inproc.LINK_PROFILES, planned
                                # per directed link by plan_link_profiles
    link_profile_seed = 0       # planner + per-link policy RNG seed

A subprocess runner that cannot emulate the profile must reject the
manifest rather than silently run it clean (validated here either way, so
a typo'd profile fails at load, not mid-run).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..libs import toml_compat


@dataclass
class NodeManifest:
    name: str
    mode: str = "validator"            # validator | full
    mempool_version: str = "v0"
    fast_sync: bool = True
    state_sync: bool = False
    privval: str = "file"              # file | tcp
    start_at: int = 0                  # 0 = start with the net
    stop_at: int = 0                   # 0 = never leave; else a clean
                                       # SIGTERM once the net reaches it
    seed_node: bool = False            # discovery entry (topology="seed")
    perturb: List[str] = field(default_factory=list)
    misbehaviors: Dict[int, str] = field(default_factory=dict)
    # fault-plane arming for this node's subprocess: exported as
    # TMTPU_FAULTS / TMTPU_FAULTS_SEED (libs/faults.py grammar), e.g.
    # faults = "wal.fsync*1+3" crashes the node at its 4th fsync
    faults: str = ""
    faults_seed: int = 0
    # crash plane: kill the node the first time it reaches this named
    # durability boundary (libs/fail.py KNOWN_FAIL_POINTS; exported as
    # TMTPU_FAIL_POINT). ONE-SHOT: a supervised relaunch drops the arming,
    # so the node dies at the boundary exactly once and then recovers.
    fail_point: str = ""
    # restart supervision (libs/supervisor.py): "never" keeps today's
    # dead-stays-dead behavior; "on-failure" relaunches non-clean exits
    # with bounded exponential backoff until max_restarts consecutive
    # fast crashes, then gives up with a crash-loop debugdump bundle
    restart_policy: str = "never"
    max_restarts: int = 3
    backoff_s: float = 1.0

    def validate(self) -> None:
        if self.mode not in ("validator", "full"):
            raise ValueError(f"{self.name}: unknown mode {self.mode!r}")
        if self.mempool_version not in ("v0", "v1", "v2"):
            raise ValueError(
                f"{self.name}: unknown mempool version {self.mempool_version!r}")
        if self.privval not in ("file", "tcp"):
            raise ValueError(f"{self.name}: unknown privval {self.privval!r}")
        for p in self.perturb:
            if p not in ("kill", "pause", "restart", "disconnect"):
                raise ValueError(f"{self.name}: unknown perturbation {p!r}")
        if self.faults:
            from ..libs.faults import KNOWN_SITES, FaultPlane, is_known_site

            try:  # fail at manifest load, not node boot
                plane = FaultPlane().configure(self.faults, self.faults_seed)
            except ValueError as e:
                raise ValueError(f"{self.name}: bad faults spec: {e}") from e
            unknown = {s for s in plane.counts() if not is_known_site(s)}
            if unknown:
                # a typo'd site arms nothing and the chaos run passes
                # vacuously — reject it where the operator can see it
                raise ValueError(
                    f"{self.name}: unknown fault site(s) {sorted(unknown)}; "
                    f"known: {sorted(KNOWN_SITES)}")
        if self.fail_point:
            from ..libs.fail import KNOWN_FAIL_POINTS

            if self.fail_point not in KNOWN_FAIL_POINTS:
                # a typo'd boundary never fires and the crash cell passes
                # vacuously — reject it where the operator can see it
                raise ValueError(
                    f"{self.name}: unknown fail point {self.fail_point!r}; "
                    f"known: {sorted(KNOWN_FAIL_POINTS)}")
            if self.restart_policy == "never":
                raise ValueError(
                    f"{self.name}: fail_point kills the node at a "
                    f"durability boundary — it needs restart_policy = "
                    f'"on-failure" to come back (or the run just loses it)')
        from ..libs.supervisor import RestartPolicy

        try:
            RestartPolicy(policy=self.restart_policy,
                          max_restarts=self.max_restarts,
                          backoff_s=self.backoff_s).validate()
        except ValueError as e:
            raise ValueError(f"{self.name}: {e}") from e
        if self.state_sync and self.start_at == 0:
            raise ValueError(
                f"{self.name}: state_sync nodes must join later (start_at > 0)")
        if self.stop_at < 0 or self.start_at < 0:
            raise ValueError(f"{self.name}: start_at/stop_at must be >= 0")
        if self.stop_at and self.stop_at <= self.start_at:
            raise ValueError(
                f"{self.name}: stop_at ({self.stop_at}) must exceed "
                f"start_at ({self.start_at}) — a node can't leave before "
                f"it joins")
        if self.seed_node and (self.start_at or self.stop_at):
            raise ValueError(
                f"{self.name}: a seed node anchors discovery; it can't "
                f"churn (start_at/stop_at must be 0)")


TOPOLOGIES = ("full_mesh", "sparse", "seed")


@dataclass
class Manifest:
    chain_id: str = "e2e-net"
    initial_height: int = 1
    load_tx_rate: int = 2
    wait_blocks: int = 6
    topology: str = "full_mesh"
    sparse_degree: int = 3
    topology_seed: int = 0
    # degraded-network plane: a named link profile (p2p.inproc
    # LINK_PROFILES) planned per directed link from one seed; "" = clean
    link_profile: str = ""
    link_profile_seed: int = 0
    validators: Dict[str, int] = field(default_factory=dict)
    nodes: List[NodeManifest] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path, "rb") as f:
            doc = toml_compat.load(f)
        return cls.from_doc(doc)

    @classmethod
    def from_doc(cls, doc: dict) -> "Manifest":
        nodes = []
        for name, nd in doc.get("node", {}).items():
            nodes.append(NodeManifest(
                name=name,
                mode=nd.get("mode", "validator"),
                mempool_version=nd.get("mempool_version", "v0"),
                fast_sync=nd.get("fast_sync", True),
                state_sync=nd.get("state_sync", False),
                privval=nd.get("privval", "file"),
                start_at=int(nd.get("start_at", 0)),
                stop_at=int(nd.get("stop_at", 0)),
                seed_node=bool(nd.get("seed_node", False)),
                perturb=list(nd.get("perturb", [])),
                misbehaviors={int(h): m
                              for h, m in nd.get("misbehaviors", {}).items()},
                faults=nd.get("faults", ""),
                faults_seed=int(nd.get("faults_seed", 0)),
                fail_point=nd.get("fail_point", ""),
                restart_policy=nd.get("restart_policy", "never"),
                max_restarts=int(nd.get("max_restarts", 3)),
                backoff_s=float(nd.get("backoff_s", 1.0)),
            ))
        m = cls(
            chain_id=doc.get("chain_id", "e2e-net"),
            initial_height=int(doc.get("initial_height", 1)),
            load_tx_rate=int(doc.get("load_tx_rate", 2)),
            wait_blocks=int(doc.get("wait_blocks", 6)),
            topology=doc.get("topology", "full_mesh"),
            sparse_degree=int(doc.get("sparse_degree", 3)),
            topology_seed=int(doc.get("topology_seed", 0)),
            link_profile=doc.get("link_profile", ""),
            link_profile_seed=int(doc.get("link_profile_seed", 0)),
            validators={k: int(v) for k, v in doc.get("validators", {}).items()},
            nodes=nodes,
        )
        m.validate()
        return m

    def validate(self) -> None:
        if not self.nodes:
            raise ValueError("manifest has no nodes")
        names = [n.name for n in self.nodes]
        if len(set(names)) != len(names):
            raise ValueError("duplicate node names")
        n_validators = sum(1 for n in self.nodes if n.mode == "validator")
        if n_validators < 1:
            raise ValueError("need at least one validator")
        for n in self.nodes:
            n.validate()
        launch_validators = [n for n in self.nodes
                             if n.mode == "validator" and n.start_at == 0]
        if not launch_validators:
            raise ValueError("need at least one validator at genesis")
        if self.topology not in TOPOLOGIES:
            raise ValueError(f"unknown topology {self.topology!r}; "
                             f"known: {TOPOLOGIES}")
        if self.link_profile:
            from ..p2p.inproc import LINK_PROFILES

            if self.link_profile not in LINK_PROFILES:
                # a typo'd profile would run the net clean and pass the
                # degradation cell vacuously — reject at load
                raise ValueError(
                    f"unknown link profile {self.link_profile!r}; "
                    f"known: {sorted(LINK_PROFILES)}")
        if self.sparse_degree < 1:
            raise ValueError("sparse_degree must be >= 1")
        if self.topology == "seed" and not any(n.seed_node
                                               for n in self.nodes):
            raise ValueError('topology "seed" needs at least one node '
                             'with seed_node = true')
        if any(n.seed_node for n in self.nodes) and self.topology != "seed":
            raise ValueError('seed_node nodes require topology = "seed"')
        # churn must not drain the quorum: validators that never leave
        # must hold > 2/3 of genesis power, or the schedule stalls the net
        powers = self.validators or {
            n.name: 10 for n in self.nodes if n.mode == "validator"}
        total = sum(powers.values())
        staying = sum(p for name, p in powers.items()
                      if not any(n.name == name and n.stop_at
                                 for n in self.nodes))
        if total and staying * 3 <= total * 2:
            raise ValueError(
                f"churn schedule drains quorum: validators that never "
                f"leave hold {staying}/{total} power (need > 2/3)")
