"""E2E testnet manifest (reference test/e2e/pkg/manifest.go:11).

TOML shape:

    chain_id = "e2e-net"
    initial_height = 1
    load_tx_rate = 2            # txs/sec during the load stage
    wait_blocks = 6             # blocks to wait after perturbations

    [validators]                # name -> voting power (defaults: all 4 @ 10)
    validator0 = 10

    [node.validator0]
    mode = "validator"          # validator | full
    mempool_version = "v2"      # v0 | v2 (v1 = legacy alias for v2)
    fast_sync = true
    state_sync = false
    privval = "file"            # file | tcp (remote signer over SecretConn)
    start_at = 0                # join the net after this height (0 = launch)
    perturb = ["kill"]          # kill | pause | restart | disconnect
    [node.validator0.misbehaviors]
    3 = "double-prevote"        # height -> misbehavior (maverick hooks)

Perturbation semantics: kill/pause/restart match the reference's
(test/e2e/runner/perturb.go:28-66). ``disconnect`` is an APPROXIMATION —
subprocess nets have no network namespace to unplug, so it is a long
SIGSTOP: peers drop the frozen node on ping timeout and re-dial after
SIGCONT. One-way partitions and asymmetric connectivity are NOT
representable; the reference uses docker network disconnect
(perturb.go:48) for true partitions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..libs import toml_compat


@dataclass
class NodeManifest:
    name: str
    mode: str = "validator"            # validator | full
    mempool_version: str = "v0"
    fast_sync: bool = True
    state_sync: bool = False
    privval: str = "file"              # file | tcp
    start_at: int = 0                  # 0 = start with the net
    perturb: List[str] = field(default_factory=list)
    misbehaviors: Dict[int, str] = field(default_factory=dict)
    # fault-plane arming for this node's subprocess: exported as
    # TMTPU_FAULTS / TMTPU_FAULTS_SEED (libs/faults.py grammar), e.g.
    # faults = "wal.fsync*1+3" crashes the node at its 4th fsync
    faults: str = ""
    faults_seed: int = 0

    def validate(self) -> None:
        if self.mode not in ("validator", "full"):
            raise ValueError(f"{self.name}: unknown mode {self.mode!r}")
        if self.mempool_version not in ("v0", "v1", "v2"):
            raise ValueError(
                f"{self.name}: unknown mempool version {self.mempool_version!r}")
        if self.privval not in ("file", "tcp"):
            raise ValueError(f"{self.name}: unknown privval {self.privval!r}")
        for p in self.perturb:
            if p not in ("kill", "pause", "restart", "disconnect"):
                raise ValueError(f"{self.name}: unknown perturbation {p!r}")
        if self.faults:
            from ..libs.faults import KNOWN_SITES, FaultPlane, is_known_site

            try:  # fail at manifest load, not node boot
                plane = FaultPlane().configure(self.faults, self.faults_seed)
            except ValueError as e:
                raise ValueError(f"{self.name}: bad faults spec: {e}") from e
            unknown = {s for s in plane.counts() if not is_known_site(s)}
            if unknown:
                # a typo'd site arms nothing and the chaos run passes
                # vacuously — reject it where the operator can see it
                raise ValueError(
                    f"{self.name}: unknown fault site(s) {sorted(unknown)}; "
                    f"known: {sorted(KNOWN_SITES)}")
        if self.state_sync and self.start_at == 0:
            raise ValueError(
                f"{self.name}: state_sync nodes must join later (start_at > 0)")


@dataclass
class Manifest:
    chain_id: str = "e2e-net"
    initial_height: int = 1
    load_tx_rate: int = 2
    wait_blocks: int = 6
    validators: Dict[str, int] = field(default_factory=dict)
    nodes: List[NodeManifest] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path, "rb") as f:
            doc = toml_compat.load(f)
        return cls.from_doc(doc)

    @classmethod
    def from_doc(cls, doc: dict) -> "Manifest":
        nodes = []
        for name, nd in doc.get("node", {}).items():
            nodes.append(NodeManifest(
                name=name,
                mode=nd.get("mode", "validator"),
                mempool_version=nd.get("mempool_version", "v0"),
                fast_sync=nd.get("fast_sync", True),
                state_sync=nd.get("state_sync", False),
                privval=nd.get("privval", "file"),
                start_at=int(nd.get("start_at", 0)),
                perturb=list(nd.get("perturb", [])),
                misbehaviors={int(h): m
                              for h, m in nd.get("misbehaviors", {}).items()},
                faults=nd.get("faults", ""),
                faults_seed=int(nd.get("faults_seed", 0)),
            ))
        m = cls(
            chain_id=doc.get("chain_id", "e2e-net"),
            initial_height=int(doc.get("initial_height", 1)),
            load_tx_rate=int(doc.get("load_tx_rate", 2)),
            wait_blocks=int(doc.get("wait_blocks", 6)),
            validators={k: int(v) for k, v in doc.get("validators", {}).items()},
            nodes=nodes,
        )
        m.validate()
        return m

    def validate(self) -> None:
        if not self.nodes:
            raise ValueError("manifest has no nodes")
        names = [n.name for n in self.nodes]
        if len(set(names)) != len(names):
            raise ValueError("duplicate node names")
        n_validators = sum(1 for n in self.nodes if n.mode == "validator")
        if n_validators < 1:
            raise ValueError("need at least one validator")
        for n in self.nodes:
            n.validate()
        launch_validators = [n for n in self.nodes
                             if n.mode == "validator" and n.start_at == 0]
        if not launch_validators:
            raise ValueError("need at least one validator at genesis")
