"""Manifest-driven end-to-end testnets (reference test/e2e/).

A TOML manifest describes an N-node network — sync modes, mempool version,
privval transport, perturbations, byzantine misbehaviors — and the runner
drives it through setup/start/load/perturb/wait/test stages with post-run
invariant checks over RPC (reference test/e2e/pkg/manifest.go:11,
test/e2e/runner/main.go, test/e2e/runner/perturb.go:28-66).
"""

from .manifest import Manifest, NodeManifest  # noqa: F401
from .runner import Runner  # noqa: F401
