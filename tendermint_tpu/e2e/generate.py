"""Randomized e2e testnet manifest generator
(reference test/e2e/generator/generate.go:16-40).

The three hand-written CI manifests are the smoke tier; this module samples
the combination space — topology x mempool version x privval transport x
sync mode x late joiners x perturbations x misbehaviors — the way the
reference's nightly matrix does, because cross-feature bugs live in the
combinations nobody thought to write down (round 4's statesync proposer bug
was exactly such a case). Same seed -> same manifests, so a failing nightly
net is reproducible from its seed.

Usage:
    python -m tendermint_tpu.e2e.generate --seed 7 --count 4 --output-dir out/
Each manifest validates against Manifest.from_doc before being written.
"""

from __future__ import annotations

import argparse
import os
import random
from typing import List, Tuple

from .manifest import Manifest

PERTURBATIONS = ["kill", "restart", "pause", "disconnect"]


def _toml_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


def generate_one(rng: random.Random, idx: int) -> Tuple[str, dict]:
    """One sampled testnet as a TOML document dict (validated by caller)."""
    n_validators = rng.choice([2, 3, 4, 4])  # small nets; 4 is the sweet spot
    doc: dict = {
        "chain_id": f"gen-{idx}",
        "load_tx_rate": rng.choice([1, 2, 4]),
        "wait_blocks": rng.choice([4, 5, 6]),
        "node": {},
    }
    # topology axis: most nets stay full mesh; some run the sparse
    # persistent-peer graph (gossip must relay) — the churn/scale regime
    if n_validators >= 3 and rng.random() < 0.3:
        doc["topology"] = "sparse"
        doc["sparse_degree"] = rng.choice([2, 3])
        doc["topology_seed"] = rng.randint(0, 999)
    perturb_budget = 2  # bound wall-clock: at most 2 perturbed nodes per net
    for v in range(n_validators):
        node = {"mode": "validator"}
        if rng.random() < 0.5:
            node["mempool_version"] = "v2"
        if rng.random() < 0.25:
            node["privval"] = "tcp"
        # never perturb validator0: the net must keep making progress while
        # others flap (with 2 validators any kill halts consensus, so skip)
        if (v > 0 and n_validators >= 3 and perturb_budget
                and rng.random() < 0.35):
            node["perturb"] = [rng.choice(PERTURBATIONS)]
            perturb_budget -= 1
        # a lone equivocator needs >=4 validators so the net keeps quorum
        # and commits the evidence instead of stalling
        if (n_validators >= 4 and v == n_validators - 1
                and rng.random() < 0.35 and "perturb" not in node):
            node["misbehaviors"] = {str(rng.randint(3, 5)): "double-prevote"}
        doc["node"][f"validator{v}"] = node

    # full nodes: a genesis follower and/or a late joiner (fast sync or
    # state sync — state_sync requires start_at > 0 per manifest rules)
    if rng.random() < 0.4:
        doc["node"]["full0"] = {
            "mode": "full",
            "mempool_version": rng.choice(["v0", "v2"]),
        }
    if rng.random() < 0.6:
        joiner = {"mode": "full", "start_at": rng.randint(5, 8)}
        if rng.random() < 0.5:
            joiner["state_sync"] = True
        if rng.random() < 0.3:
            # full churn arc: join late AND leave before the run ends
            joiner["stop_at"] = joiner["start_at"] + rng.randint(4, 6)
        doc["node"][f"sync{idx}"] = joiner
    # a genesis full node may leave mid-run (validators keep quorum: the
    # manifest validator requires >2/3 of power to never stop)
    if "full0" in doc["node"] and rng.random() < 0.3:
        doc["node"]["full0"]["stop_at"] = rng.randint(6, 9)
    return doc["chain_id"], doc


def doc_to_toml(doc: dict) -> str:
    lines = [f"# generated manifest (tendermint_tpu.e2e.generate)"]
    for k in ("chain_id", "initial_height", "load_tx_rate", "wait_blocks",
              "topology", "sparse_degree", "topology_seed"):
        if k in doc:
            lines.append(f"{k} = {_toml_str(doc[k])}")
    if doc.get("validators"):
        lines.append("\n[validators]")
        for name, power in doc["validators"].items():
            lines.append(f"{name} = {power}")
    for name, node in doc.get("node", {}).items():
        lines.append(f"\n[node.{name}]")
        for k, v in node.items():
            if k == "misbehaviors":
                continue
            if k == "perturb":
                lines.append(
                    f"perturb = [{', '.join(_toml_str(p) for p in v)}]")
            else:
                lines.append(f"{k} = {_toml_str(v)}")
        if "misbehaviors" in node:
            lines.append(f"[node.{name}.misbehaviors]")
            for h, m in node["misbehaviors"].items():
                lines.append(f"{h} = {_toml_str(m)}")
    return "\n".join(lines) + "\n"


def generate(seed: int, count: int = 4) -> List[Tuple[str, Manifest, str]]:
    """count validated (name, Manifest, toml_text) tuples from one seed."""
    rng = random.Random(seed)
    out = []
    for idx in range(count):
        name, doc = generate_one(rng, idx)
        toml_text = doc_to_toml(doc)
        # round-trip through the TOML parser so the written file is what the
        # runner will actually load
        from ..libs import toml_compat

        manifest = Manifest.from_doc(toml_compat.loads(toml_text))
        out.append((name, manifest, toml_text))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(
        description="generate randomized e2e testnet manifests")
    ap.add_argument("--seed", type=int, required=True)
    ap.add_argument("--count", type=int, default=4)
    ap.add_argument("--output-dir", default="e2e-generated")
    args = ap.parse_args()
    os.makedirs(args.output_dir, exist_ok=True)
    for name, _m, toml_text in generate(args.seed, args.count):
        path = os.path.join(args.output_dir, f"{name}.toml")
        with open(path, "w") as f:
            f.write(toml_text)
        print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
