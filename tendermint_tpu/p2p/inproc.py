"""In-process transport: whole multi-node networks in one asyncio loop
(the reference's p2p test utilities — MakeConnectedSwitches over net.Pipe,
p2p/test_util.go). The production TCP transport shares the Peer surface.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from .base import Peer
from .switch import Switch

logger = logging.getLogger("tmtpu.p2p.inproc")


class InProcPeer(Peer):
    """One side of a connected pair; sends enqueue into the remote's pump."""

    def __init__(self, peer_id: str, outbound: bool):
        super().__init__(peer_id, outbound)
        self._remote: Optional["InProcPeer"] = None
        self._recv_queue: "asyncio.Queue[Tuple[int, bytes]]" = asyncio.Queue(maxsize=10000)
        self._running = True
        self._pump_task: Optional[asyncio.Task] = None

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self.try_send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        if not self._running or self._remote is None:
            return False
        try:
            self._remote._recv_queue.put_nowait((channel_id, msg))
            return True
        except asyncio.QueueFull:
            return False

    def is_running(self) -> bool:
        return self._running

    async def stop(self) -> None:
        self._running = False
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except asyncio.CancelledError:
                pass

    async def _pump(self, switch: Switch) -> None:
        """Deliver inbound messages into the owning switch."""
        while self._running:
            channel_id, msg = await self._recv_queue.get()
            await switch.dispatch(channel_id, self, msg)
            await asyncio.sleep(0)  # fairness under sustained load


class InProcNetwork:
    """Registry + wiring of in-proc switches (MakeConnectedSwitches analog)."""

    def __init__(self):
        self.switches: Dict[str, Switch] = {}

    def add_switch(self, switch: Switch) -> None:
        self.switches[switch.node_id] = switch

    async def connect(self, id_a: str, id_b: str) -> None:
        """Create a bidirectional pair and register with both switches."""
        sw_a, sw_b = self.switches[id_a], self.switches[id_b]
        peer_of_b = InProcPeer(id_b, outbound=True)   # a's view of b
        peer_of_a = InProcPeer(id_a, outbound=False)  # b's view of a
        peer_of_b._remote = peer_of_a
        peer_of_a._remote = peer_of_b
        peer_of_b._pump_task = asyncio.create_task(peer_of_b._pump(sw_a))
        peer_of_a._pump_task = asyncio.create_task(peer_of_a._pump(sw_b))
        await sw_a.add_peer(peer_of_b)
        await sw_b.add_peer(peer_of_a)

    async def connect_all(self) -> None:
        ids = list(self.switches)
        for i, a in enumerate(ids):
            for b in ids[i + 1:]:
                await self.connect(a, b)

    async def disconnect(self, id_a: str, id_b: str) -> None:
        """Sever the pair in both directions (perturbation support)."""
        sw_a, sw_b = self.switches[id_a], self.switches[id_b]
        pa = sw_a.peers.get(id_b)
        pb = sw_b.peers.get(id_a)
        if pa is not None:
            await sw_a.stop_peer_gracefully(pa)
        if pb is not None:
            await sw_b.stop_peer_gracefully(pb)

    async def stop(self) -> None:
        for sw in self.switches.values():
            await sw.stop()
