"""In-process transport: whole multi-node networks in one asyncio loop
(the reference's p2p test utilities — MakeConnectedSwitches over net.Pipe,
p2p/test_util.go). The production TCP transport shares the Peer surface.

Chaos controls: every DIRECTED link (a's peer object for b carries the
a→b direction) can take a :class:`LinkPolicy` — seeded drop / duplicate /
reorder / delay / jitter plus a partition blackhole — so a 4-node
consensus net can be run under deterministic 10% loss, partitioned, and
healed, all inside one test. Policies are applied at ``try_send`` time;
with no policy the path is byte-identical to the original direct enqueue.

Degraded-network profiles: :data:`LINK_PROFILES` names the knob sets for
the hard regimes the partially-synchronous model actually allows —
``wan`` (latency + jitter + light loss), ``gray`` (heavy loss, NOT a
blackhole: some traffic still leaks through, so peers never see a clean
disconnect), and ``asym`` (one direction degraded while the reverse stays
clean). :func:`plan_link_profiles` is the pure seeded planner that maps
every directed link to its knobs — same (ids, profile, seed) → same plan —
and ``InProcNetwork.apply_link_plan`` attaches it to a live net. One-way
partitions (``partition_oneway``) and cut-scoped healing (``heal`` with
groups) round out the plane: healing never replaces policy objects, so
the surviving direction's RNG stream keeps replaying.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import random
import zlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..libs.faults import faults
from .base import Peer
from .switch import Switch

logger = logging.getLogger("tmtpu.p2p.inproc")


class LinkPolicy:
    """Deterministic chaos policy for one directed link.

    All randomness comes from one ``random.Random`` seeded by
    (seed, src, dst), so a run replays exactly: the i-th send over this
    link sees the same fate every time regardless of scheduling elsewhere.
    ``blocked`` models a network partition: sends are blackholed (the
    sender still sees success — a partitioned wire gives no feedback).

    ``delay_s`` is the base one-way latency; ``jitter_s`` adds a seeded
    uniform draw in [0, jitter_s) per delivered copy, modeling WAN queueing
    variance. With ``jitter_s == 0`` the RNG stream is byte-identical to a
    policy built before jitter existed (no extra draw is consumed), so
    seeded replays of older schedules still hold.
    """

    __slots__ = ("drop_p", "dup_p", "reorder_p", "delay_s", "jitter_s",
                 "blocked", "profile", "rng", "stats")

    def __init__(self, src: str = "", dst: str = "", seed: int = 0,
                 drop_p: float = 0.0, dup_p: float = 0.0,
                 reorder_p: float = 0.0, delay_s: float = 0.0,
                 jitter_s: float = 0.0, blocked: bool = False,
                 profile: str = ""):
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.reorder_p = reorder_p
        self.delay_s = delay_s
        self.jitter_s = jitter_s
        self.blocked = blocked
        self.profile = profile
        self.rng = random.Random(zlib.crc32(f"{seed}|{src}|{dst}".encode()))
        self.stats = collections.Counter()

    def plan(self) -> Optional[list]:
        """Decide one message's fate: None = drop/blackhole, else a list of
        per-copy delivery delays (0.0 = immediate). Pure decision — the
        peer does the queueing — so determinism is testable without a
        loop."""
        if self.blocked:
            self.stats["blackholed"] += 1
            return None
        r = self.rng
        if self.drop_p and r.random() < self.drop_p:
            self.stats["dropped"] += 1
            return None
        copies = 1
        if self.dup_p and r.random() < self.dup_p:
            copies = 2
            self.stats["duplicated"] += 1
        delays = []
        for _ in range(copies):
            delay = self.delay_s
            if self.jitter_s:
                delay += r.uniform(0.0, self.jitter_s)
                self.stats["jittered"] += 1
            if self.reorder_p and r.random() < self.reorder_p:
                # hold this copy just long enough for later sends to
                # overtake it (queue pumps drain in well under a ms)
                delay += r.uniform(0.001, 0.005)
                self.stats["reordered"] += 1
            if delay:
                self.stats["delayed"] += 1
            delays.append(delay)
        self.stats["delivered"] += copies
        return delays


#: named knob sets for one DIRECTED link under each degraded-network
#: profile (the e2e manifest validates against these same names):
#:   wan   continental RTT with queueing variance and light loss
#:   gray  heavy loss that still leaks traffic — peers never see a clean
#:         disconnect, the regime that defeats naive failure detectors
#:   asym  knobs for the DEGRADED direction of an asymmetric pair; the
#:         planner leaves the reverse direction clean
LINK_PROFILES: Dict[str, Dict[str, float]] = {
    "wan":  {"delay_s": 0.030, "jitter_s": 0.040, "drop_p": 0.01,
             "reorder_p": 0.05},
    "gray": {"delay_s": 0.010, "jitter_s": 0.020, "drop_p": 0.60},
    "asym": {"delay_s": 0.020, "jitter_s": 0.030, "drop_p": 0.45},
}


def plan_link_profiles(ids: List[str], profile: str,
                       seed: int = 0) -> Dict[Tuple[str, str], Dict]:
    """Pure seeded planner: map every directed link among ``ids`` to the
    knob dict it should run under ``profile``. Same (ids, profile, seed) →
    same plan, independent of any live net. ``wan`` and ``gray`` degrade
    every direction uniformly; ``asym`` picks — per unordered pair, from
    the planner RNG — ONE direction to degrade and leaves the reverse
    clean (absent from the plan). Every knob dict carries ``profile`` so
    live policies are attributable in stats and fingerprints."""
    if profile not in LINK_PROFILES:
        raise ValueError(
            f"unknown link profile {profile!r}; known: "
            f"{sorted(LINK_PROFILES)}")
    knobs = dict(LINK_PROFILES[profile], profile=profile)
    ids = sorted(ids)
    plan: Dict[Tuple[str, str], Dict] = {}
    rng = random.Random(zlib.crc32(f"linkplan|{profile}|{seed}".encode()))
    for i, a in enumerate(ids):
        for b in ids[i + 1:]:
            if profile == "asym":
                src, dst = (a, b) if rng.random() < 0.5 else (b, a)
                plan[(src, dst)] = dict(knobs)
            else:
                plan[(a, b)] = dict(knobs)
                plan[(b, a)] = dict(knobs)
    return plan


def sparse_edges(ids: List[str], degree: int = 3,
                 seed: int = 0) -> List[Tuple[str, str]]:
    """Deterministic connected sparse graph over ``ids``: a ring (so the
    graph is connected by construction) plus seeded random chords until the
    average degree reaches ``degree``. Pure — same (ids, degree, seed) →
    same edge list — so an e2e runner and an in-proc chaos net derive the
    SAME persistent-peer graph. Returns sorted (a, b) pairs with a < b."""
    ids = sorted(ids)
    n = len(ids)
    if n < 2:
        return []
    edges: Set[Tuple[str, str]] = set()
    for i in range(n):  # the ring
        a, b = ids[i], ids[(i + 1) % n]
        if a != b:
            edges.add((min(a, b), max(a, b)))
    want = min(n * max(2, degree) // 2, n * (n - 1) // 2)
    rng = random.Random(zlib.crc32(f"sparse|{seed}|{n}".encode()))
    attempts = 0
    while len(edges) < want and attempts < 20 * want:
        attempts += 1
        a, b = rng.sample(ids, 2)
        edges.add((min(a, b), max(a, b)))
    return sorted(edges)


class InProcPeer(Peer):
    """One side of a connected pair; sends enqueue into the remote's pump."""

    def __init__(self, peer_id: str, outbound: bool):
        super().__init__(peer_id, outbound)
        self._remote: Optional["InProcPeer"] = None
        self._recv_queue: "asyncio.Queue[Tuple[int, bytes]]" = asyncio.Queue(maxsize=10000)
        self._running = True
        self._pump_task: Optional[asyncio.Task] = None
        #: chaos policy for the direction this peer object sends in
        #: (owner → remote); None = the original zero-overhead path
        self.policy: Optional[LinkPolicy] = None

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self.try_send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        if not self._running or self._remote is None:
            return False
        pol = self.policy
        if pol is None and not faults.enabled:
            return self._deliver(channel_id, msg)
        # generic env-armed sites (TMTPU_FAULTS=net.drop@p / net.corrupt@p):
        # drops ride the same path as a policy drop; corruption tampers the
        # payload IN FLIGHT (a Byzantine wire) so the receiver's decode /
        # signature / merkle checks run against the flipped bits. The
        # lock-free armed() probes keep chaos runs arming only
        # storage/device sites off fire()'s lock on this per-message path
        if faults.armed("net.drop") and faults.fire("net.drop"):
            return True
        if faults.armed("net.corrupt"):
            msg = faults.mutate("net.corrupt", msg)
        if pol is None:
            return self._deliver(channel_id, msg)
        delays = pol.plan()
        if delays is None:
            return True  # dropped/blackholed: the wire gives no feedback
        ok = True
        for delay in delays:
            if delay <= 0.0:
                ok = self._deliver(channel_id, msg) and ok
            else:
                self._deliver_later(delay, channel_id, msg)
        return ok

    def _deliver(self, channel_id: int, msg: bytes) -> bool:
        try:
            self._remote._recv_queue.put_nowait((channel_id, msg))
            return True
        except asyncio.QueueFull:
            return False

    def _deliver_later(self, delay: float, channel_id: int, msg: bytes) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._deliver(channel_id, msg)  # no loop: deliver inline
            return

        def _fire():
            if self._running and self._remote is not None:
                self._deliver(channel_id, msg)

        loop.call_later(delay, _fire)

    def is_running(self) -> bool:
        return self._running

    async def stop(self) -> None:
        self._running = False
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except asyncio.CancelledError:
                pass

    async def _pump(self, switch: Switch) -> None:
        """Deliver inbound messages into the owning switch."""
        while self._running:
            channel_id, msg = await self._recv_queue.get()
            await switch.dispatch(channel_id, self, msg)
            await asyncio.sleep(0)  # fairness under sustained load


class InProcNetwork:
    """Registry + wiring of in-proc switches (MakeConnectedSwitches analog)."""

    def __init__(self):
        self.switches: Dict[str, Switch] = {}
        #: directed links: (src node, dst node) -> the src-owned peer
        #: object whose try_send covers that direction
        self.links: Dict[Tuple[str, str], InProcPeer] = {}
        #: nodes that left ON PURPOSE (remove_node): excluded from
        #: reconnect_missing()/connect_all() until they re-join via
        #: add_node — a clean leave must not read as a link failure
        self.departed: Set[str] = set()

    def add_switch(self, switch: Switch) -> None:
        self.switches[switch.node_id] = switch
        self.departed.discard(switch.node_id)

    async def connect(self, id_a: str, id_b: str) -> None:
        """Create a bidirectional pair and register with both switches."""
        sw_a, sw_b = self.switches[id_a], self.switches[id_b]
        peer_of_b = InProcPeer(id_b, outbound=True)   # a's view of b
        peer_of_a = InProcPeer(id_a, outbound=False)  # b's view of a
        peer_of_b._remote = peer_of_a
        peer_of_a._remote = peer_of_b
        peer_of_b._pump_task = asyncio.create_task(peer_of_b._pump(sw_a))
        peer_of_a._pump_task = asyncio.create_task(peer_of_a._pump(sw_b))
        self.links[(id_a, id_b)] = peer_of_b
        self.links[(id_b, id_a)] = peer_of_a
        await sw_a.add_peer(peer_of_b)
        await sw_b.add_peer(peer_of_a)

    async def connect_all(self) -> None:
        ids = list(self.switches)
        for i, a in enumerate(ids):
            for b in ids[i + 1:]:
                await self.connect(a, b)

    async def connect_topology(self, topology: str = "full_mesh",
                               degree: int = 3, seed: int = 0) -> int:
        """Wire the registered switches per ``topology``: ``full_mesh``
        (every pair) or ``sparse`` (ring + seeded chords, ~``degree`` links
        per node — the persistent-peer graph shape a 32-node fleet actually
        runs, where gossip must relay multi-hop). Returns pairs wired."""
        if topology == "full_mesh":
            await self.connect_all()
            return len(self.links) // 2
        if topology != "sparse":
            raise ValueError(f"unknown topology {topology!r}")
        edges = sparse_edges(sorted(self.switches), degree=degree, seed=seed)
        for a, b in edges:
            if not self.connected(a, b):
                await self.connect(a, b)
        return len(edges)

    async def disconnect(self, id_a: str, id_b: str) -> None:
        """Sever the pair in both directions (perturbation support)."""
        sw_a, sw_b = self.switches[id_a], self.switches[id_b]
        pa = sw_a.peers.get(id_b)
        pb = sw_b.peers.get(id_a)
        self.links.pop((id_a, id_b), None)
        self.links.pop((id_b, id_a), None)
        if pa is not None:
            await sw_a.stop_peer_gracefully(pa)
        if pb is not None:
            await sw_b.stop_peer_gracefully(pb)

    # -- live membership -----------------------------------------------------

    async def add_node(self, switch: Switch,
                       connect_to: Optional[Iterable[str]] = None) -> None:
        """Register a switch at RUNTIME and wire it into the live net:
        connect to every current member (full-mesh entry) or, for sparse
        topologies / discovery entry, only to ``connect_to``. A previously
        departed id re-joining is un-marked. The switch should already be
        started (its reactors greet peers via add_peer)."""
        self.add_switch(switch)
        targets = (list(connect_to) if connect_to is not None
                   else [i for i in self.switches if i != switch.node_id])
        for other in targets:
            if other == switch.node_id or other not in self.switches:
                continue
            if not self.connected(other, switch.node_id):
                await self.connect(other, switch.node_id)

    async def remove_node(self, node_id: str) -> int:
        """Depart a node cleanly: sever every link it holds (both
        directions drained), drop its switch from the registry, and mark it
        departed so reconnect_missing()/connect_all() stop trying to
        re-wire it. LinkPolicy objects on SURVIVING links are untouched
        (their RNG streams keep replaying). Returns pairs severed. The
        caller still owns stopping the node's own switch/consensus."""
        pairs = sorted({tuple(sorted(k)) for k in self.links
                        if node_id in k})
        for id_a, id_b in pairs:
            if id_a in self.switches and id_b in self.switches:
                await self.disconnect(id_a, id_b)
            else:  # counterpart already gone: just drop the stale entries
                self.links.pop((id_a, id_b), None)
                self.links.pop((id_b, id_a), None)
        self.switches.pop(node_id, None)
        self.departed.add(node_id)
        return len(pairs)

    def connected(self, id_a: str, id_b: str) -> bool:
        """Both switches hold a live peer object for the other side."""
        return (id_b in self.switches[id_a].peers
                and id_a in self.switches[id_b].peers)

    async def reconnect_missing(self) -> int:
        """Re-establish any severed pair — the in-proc analog of persistent-
        peer redial. A corrupted message makes the receiver drop the link
        (stop_peer_for_error); without this, adversarial chaos runs bleed
        connectivity until the net partitions itself. Existing LinkPolicy
        objects (and their RNG streams) carry over to the fresh peers so a
        seeded chaos schedule survives reconnects — PER DIRECTION: an
        asymmetric pair (src→dst blocked, dst→src seeded-lossy) rewires
        with each direction keeping its own policy object, so a one-way
        partition survives a redial exactly as asymmetric. Intentionally-departed
        nodes (remove_node) are skipped — redialing them would make clean
        leave impossible and mask real link failures in chaos stats.
        Returns pairs rewired."""
        count = 0
        pairs = {tuple(sorted(k)) for k in self.links}
        for id_a, id_b in sorted(pairs):
            if id_a in self.departed or id_b in self.departed:
                continue
            if id_a not in self.switches or id_b not in self.switches:
                continue
            if self.connected(id_a, id_b):
                continue
            pol_ab = self.links.get((id_a, id_b))
            pol_ba = self.links.get((id_b, id_a))
            pol_ab = pol_ab.policy if pol_ab is not None else None
            pol_ba = pol_ba.policy if pol_ba is not None else None
            await self.disconnect(id_a, id_b)  # clear any half-open side
            await self.connect(id_a, id_b)
            self.links[(id_a, id_b)].policy = pol_ab
            self.links[(id_b, id_a)].policy = pol_ba
            count += 1
        return count

    # -- chaos controls ------------------------------------------------------

    def set_link_policy(self, src: str, dst: str, seed: int = 0,
                        **kw) -> LinkPolicy:
        """Attach a fresh seeded policy to the directed link src→dst."""
        peer = self.links[(src, dst)]
        peer.policy = LinkPolicy(src, dst, seed=seed, **kw)
        return peer.policy

    def set_loss(self, drop_p: float, seed: int = 0, **kw) -> None:
        """Seeded loss (plus any other policy knobs) on EVERY directed
        link. Per-link RNGs derive from (seed, src, dst), so the whole-net
        schedule replays exactly for a given seed."""
        for (src, dst) in list(self.links):
            self.set_link_policy(src, dst, seed=seed, drop_p=drop_p, **kw)

    def apply_link_plan(self, plan: Dict[Tuple[str, str], Dict],
                        seed: int = 0) -> int:
        """Attach a :func:`plan_link_profiles` plan to the live net: each
        planned directed link gets a fresh seeded policy with the planned
        knobs; directed links absent from the plan are left untouched
        (clean under ``asym``). Returns policies attached."""
        count = 0
        for (src, dst), kw in sorted(plan.items()):
            if (src, dst) in self.links:
                self.set_link_policy(src, dst, seed=seed, **kw)
                count += 1
        return count

    def apply_profile(self, profile: str, seed: int = 0) -> int:
        """Plan + apply a named profile over every current switch."""
        plan = plan_link_profiles(sorted(self.switches), profile, seed=seed)
        return self.apply_link_plan(plan, seed=seed)

    def clear_policies(self) -> None:
        for peer in self.links.values():
            peer.policy = None

    def partition(self, group_a: Iterable[str],
                  group_b: Optional[Iterable[str]] = None) -> None:
        """Blackhole every link crossing the cut (both directions).
        ``group_b`` defaults to all other switches. Existing policies on
        crossing links keep their seed/loss knobs and gain ``blocked``;
        links without a policy get a block-only one."""
        a: Set[str] = set(group_a)
        b: Set[str] = (set(group_b) if group_b is not None
                       else set(self.switches) - a)
        for (src, dst), peer in self.links.items():
            if (src in a and dst in b) or (src in b and dst in a):
                if peer.policy is None:
                    peer.policy = LinkPolicy(src, dst, blocked=True)
                else:
                    peer.policy.blocked = True

    def partition_oneway(self, src_group: Iterable[str],
                         dst_group: Optional[Iterable[str]] = None) -> int:
        """Blackhole ONLY the src→dst direction of links crossing the
        cut — the reverse direction keeps flowing, its policy object (and
        RNG stream) untouched. This is the asymmetric-connectivity regime
        TCP-based failure detectors misread: dst still hears from src but
        src gets no acks back. Returns directed links blocked."""
        a: Set[str] = set(src_group)
        b: Set[str] = (set(dst_group) if dst_group is not None
                       else set(self.switches) - a)
        count = 0
        for (src, dst), peer in self.links.items():
            if src in a and dst in b:
                if peer.policy is None:
                    peer.policy = LinkPolicy(src, dst, blocked=True)
                else:
                    peer.policy.blocked = True
                count += 1
        return count

    def heal(self, group_a: Optional[Iterable[str]] = None,
             group_b: Optional[Iterable[str]] = None) -> int:
        """Unblock partitioned links: every link by default, or — given
        ``group_a`` (and optionally ``group_b``) — only links crossing
        that cut, both directions. Healing only flips ``blocked`` flags;
        policy objects are NEVER replaced, so loss/delay knobs and RNG
        streams survive — a direction that was never blocked (one-way
        partition) is a no-op flip and its seeded schedule continues
        undisturbed. Returns directed links unblocked."""
        if group_a is None:
            sel = None
        else:
            a: Set[str] = set(group_a)
            b: Set[str] = (set(group_b) if group_b is not None
                           else set(self.switches) - a)
            sel = (a, b)
        count = 0
        for (src, dst), peer in self.links.items():
            if sel is not None:
                a, b = sel
                if not ((src in a and dst in b)
                        or (src in b and dst in a)):
                    continue
            if peer.policy is not None and peer.policy.blocked:
                peer.policy.blocked = False
                count += 1
        return count

    def chaos_stats(self) -> collections.Counter:
        """Aggregate per-link policy counters (dropped/duplicated/...)."""
        total: collections.Counter = collections.Counter()
        for peer in self.links.values():
            if peer.policy is not None:
                total.update(peer.policy.stats)
        return total

    async def stop(self) -> None:
        for sw in self.switches.values():
            await sw.stop()
