"""Distributed communication backend (reference p2p/, SURVEY.md §2.4).

The Reactor/Switch/Peer abstraction is preserved from the reference so
transports are swappable: `inproc` wires whole networks inside one process
(the test transport the reference builds with net.Pipe), `tcp` is the real
authenticated multiplexed transport (SecretConnection + MConnection).
"""

from .base import ChannelDescriptor, Envelope, Peer, Reactor  # noqa: F401
from .switch import Switch  # noqa: F401
from .inproc import InProcNetwork  # noqa: F401
from .key import NodeKey, pubkey_to_id  # noqa: F401
from .netaddress import NetAddress, parse_peer_list  # noqa: F401
from .node_info import NodeInfo  # noqa: F401

try:  # the TCP transport needs `cryptography` (x25519 handshake); the
    # in-proc transport, reactors, and sync machinery must keep working
    # without it (slim containers, unit tests)
    from .transport import TCPTransport  # noqa: F401
except ImportError as _tcp_err:  # pragma: no cover - environment-dependent
    _TCP_IMPORT_ERROR = _tcp_err

    class TCPTransport:  # type: ignore[no-redef]
        """Unavailable: constructing it names the missing dependency
        instead of failing with an opaque NoneType error at node start."""

        def __init__(self, *_a, **_kw):
            raise ImportError(
                "TCPTransport requires the 'cryptography' package "
                f"(import failed: {_TCP_IMPORT_ERROR})")

# Channel IDs (reference consensus/reactor.go:26-29, mempool/mempool.go:14,
# evidence/reactor.go:16, blockchain/v0/reactor.go, statesync/reactor.go:22)
PEX_CHANNEL = 0x00
STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23
MEMPOOL_CHANNEL = 0x30
EVIDENCE_CHANNEL = 0x38
BLOCKCHAIN_CHANNEL = 0x40
SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
