"""Switch: reactor registry + peer lifecycle (reference p2p/switch.go:69)."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from .base import ChannelDescriptor, Peer, Reactor

logger = logging.getLogger("tmtpu.p2p")


class Switch:
    def __init__(self, node_id: str, transport=None, trust_store=None):
        self.node_id = node_id
        self.transport = transport  # TCPTransport or None (in-proc)
        self.reactors: Dict[str, Reactor] = {}
        self._reactors_by_ch: Dict[int, Reactor] = {}
        self.peers: Dict[str, Peer] = {}
        self._running = False
        self._dial_tasks: Dict[str, asyncio.Task] = {}  # persistent redials
        # optional p2p.trust.TrustMetricStore (reference p2p/trust/store.go):
        # good/bad events feed EWMA scores; quarantined peers are refused on
        # dial AND accept until their ban lapses
        self.trust_store = trust_store
        # persistent peers are exempt from trust-quarantine refusals: they
        # are operator-configured (the reference treats persistent peers as
        # unconditional), and a transient flap must not 10-minute-ban the
        # validator we are told to stay connected to. Their events still
        # feed the metric for observability.
        self._persistent_ids: set = set()

    def _quarantined(self, peer_id: str) -> bool:
        return (self.trust_store is not None
                and peer_id not in self._persistent_ids
                and self.trust_store.banned(peer_id))

    # -- reactors (switch.go:163 AddReactor) -------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        for ch in reactor.get_channels():
            if ch.id in self._reactors_by_ch:
                raise ValueError(
                    f"channel {ch.id:#x} already registered by "
                    f"{self._reactors_by_ch[ch.id].name}")
            self._reactors_by_ch[ch.id] = reactor
        self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Optional[Reactor]:
        return self.reactors.get(name)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()

    async def stop(self) -> None:
        self._running = False
        for t in self._dial_tasks.values():
            t.cancel()
        self._dial_tasks.clear()
        # peers BEFORE transport: Server.wait_closed (py3.12) blocks until
        # every accepted connection is closed, and those sockets are owned by
        # the peers' SecretConnections
        for peer in list(self.peers.values()):
            await self.stop_peer_gracefully(peer)
        if self.transport is not None:
            await self.transport.close()
        for reactor in self.reactors.values():
            await reactor.stop()
        if self.trust_store is not None:
            self.trust_store.save()

    # -- TCP transport wiring (switch.go:665 acceptRoutine, :430 reconnect) --

    async def listen(self, host: str, port: int):
        """Start the transport's accept loop; inbound peers auto-register."""
        if self.transport is None:
            raise RuntimeError("switch has no transport")
        return await self.transport.listen(host, port, self._on_inbound_peer)

    async def _on_inbound_peer(self, peer) -> None:
        if not self._running or peer.id in self.peers or peer.id == self.node_id:
            await peer.stop()
            return
        if self._quarantined(peer.id):
            logger.info("%s: refusing quarantined peer %s", self.node_id[:8],
                        peer.id[:8])
            await peer.stop()
            return
        peer.bind(self)
        peer.start()
        await self.add_peer(peer)

    async def dial_peer(self, addr, persistent: bool = False) -> bool:
        """One dial attempt; -> True when the peer is registered."""
        if self.transport is None:
            raise RuntimeError("switch has no transport")
        if addr.id in self.peers or addr.id == self.node_id:
            return False
        if persistent:
            self._persistent_ids.add(addr.id)
        if self._quarantined(addr.id):
            logger.debug("%s: not dialing quarantined peer %s",
                         self.node_id[:8], addr.id[:8])
            return False
        try:
            peer = await self.transport.dial(addr, persistent=persistent)
        except Exception as e:
            logger.debug("%s: dial %s failed: %s", self.node_id[:8], addr, e)
            return False
        if peer.id in self.peers:  # simultaneous connect race: keep existing
            await peer.stop()
            return False
        peer.bind(self)
        peer.start()
        await self.add_peer(peer)
        return True

    def dial_peers_async(self, addrs, persistent: bool = False) -> None:
        """(switch.go DialPeersAsync) fire-and-forget with reconnect for
        persistent peers (exponential backoff, switch.go:430)."""
        for addr in addrs:
            if persistent:
                # register before the first dial so an inbound connection
                # from the same peer is already exempt from quarantine
                self._persistent_ids.add(addr.id)
            if addr.id in self._dial_tasks:
                continue
            t = asyncio.create_task(self._dial_loop(addr, persistent))
            self._dial_tasks[addr.id] = t

    async def _dial_loop(self, addr, persistent: bool) -> None:
        backoff = 1.0
        try:
            while self._running if persistent else True:
                if addr.id in self.peers:
                    if not persistent:
                        return
                    await asyncio.sleep(1.0)
                    continue
                ok = await self.dial_peer(addr, persistent=persistent)
                if ok:
                    if not persistent:
                        return
                    backoff = 1.0
                    await asyncio.sleep(1.0)
                    continue
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                if not persistent and backoff > 8.0:
                    return
        except asyncio.CancelledError:
            raise
        finally:
            self._dial_tasks.pop(addr.id, None)

    # -- peers -------------------------------------------------------------

    async def add_peer(self, peer: Peer) -> None:
        """(switch.go:684 addPeer)"""
        for reactor in self.reactors.values():
            peer = reactor.init_peer(peer)
        self.peers[peer.id] = peer
        for reactor in self.reactors.values():
            await reactor.add_peer(peer)
        if self.trust_store is not None:
            self.trust_store.peer_good(peer.id)
        logger.debug("%s: added peer %s (%d total)", self.node_id[:8], peer.id[:8],
                     len(self.peers))

    async def stop_peer_for_error(self, peer: Peer, reason: str) -> None:
        """(switch.go:367)"""
        logger.info("%s: stopping peer %s for error: %s", self.node_id[:8],
                    peer.id[:8], reason)
        if self.trust_store is not None:
            self.trust_store.peer_bad(peer.id)
        await self._stop_and_remove_peer(peer, reason)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._stop_and_remove_peer(peer, "graceful stop")

    async def _stop_and_remove_peer(self, peer: Peer, reason: str) -> None:
        if peer.id not in self.peers:
            return
        del self.peers[peer.id]
        await peer.stop()
        for reactor in self.reactors.values():
            await reactor.remove_peer(peer, reason)

    def num_peers(self) -> int:
        return len(self.peers)

    # -- broadcast (switch.go:272) -----------------------------------------

    def broadcast(self, channel_id: int, msg: bytes) -> None:
        for peer in list(self.peers.values()):
            peer.try_send(channel_id, msg)

    # -- inbound dispatch (called by transports) ---------------------------

    async def dispatch(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        reactor = self._reactors_by_ch.get(channel_id)
        if reactor is None:
            logger.warning("no reactor for channel %#x", channel_id)
            return
        try:
            await reactor.receive(channel_id, peer, msg_bytes)
        except Exception as e:
            logger.exception("reactor %s receive error from %s", reactor.name, peer.id[:8])
            await self.stop_peer_for_error(peer, str(e))
