"""Switch: reactor registry + peer lifecycle (reference p2p/switch.go:69)."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from .base import ChannelDescriptor, Peer, Reactor

logger = logging.getLogger("tmtpu.p2p")


class Switch:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self.reactors: Dict[str, Reactor] = {}
        self._reactors_by_ch: Dict[int, Reactor] = {}
        self.peers: Dict[str, Peer] = {}
        self._running = False

    # -- reactors (switch.go:163 AddReactor) -------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        for ch in reactor.get_channels():
            if ch.id in self._reactors_by_ch:
                raise ValueError(
                    f"channel {ch.id:#x} already registered by "
                    f"{self._reactors_by_ch[ch.id].name}")
            self._reactors_by_ch[ch.id] = reactor
        self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Optional[Reactor]:
        return self.reactors.get(name)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()

    async def stop(self) -> None:
        self._running = False
        for peer in list(self.peers.values()):
            await self.stop_peer_gracefully(peer)
        for reactor in self.reactors.values():
            await reactor.stop()

    # -- peers -------------------------------------------------------------

    async def add_peer(self, peer: Peer) -> None:
        """(switch.go:684 addPeer)"""
        for reactor in self.reactors.values():
            peer = reactor.init_peer(peer)
        self.peers[peer.id] = peer
        for reactor in self.reactors.values():
            await reactor.add_peer(peer)
        logger.debug("%s: added peer %s (%d total)", self.node_id[:8], peer.id[:8],
                     len(self.peers))

    async def stop_peer_for_error(self, peer: Peer, reason: str) -> None:
        """(switch.go:367)"""
        logger.info("%s: stopping peer %s for error: %s", self.node_id[:8],
                    peer.id[:8], reason)
        await self._stop_and_remove_peer(peer, reason)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._stop_and_remove_peer(peer, "graceful stop")

    async def _stop_and_remove_peer(self, peer: Peer, reason: str) -> None:
        if peer.id not in self.peers:
            return
        del self.peers[peer.id]
        await peer.stop()
        for reactor in self.reactors.values():
            await reactor.remove_peer(peer, reason)

    def num_peers(self) -> int:
        return len(self.peers)

    # -- broadcast (switch.go:272) -----------------------------------------

    def broadcast(self, channel_id: int, msg: bytes) -> None:
        for peer in list(self.peers.values()):
            peer.try_send(channel_id, msg)

    # -- inbound dispatch (called by transports) ---------------------------

    async def dispatch(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        reactor = self._reactors_by_ch.get(channel_id)
        if reactor is None:
            logger.warning("no reactor for channel %#x", channel_id)
            return
        try:
            await reactor.receive(channel_id, peer, msg_bytes)
        except Exception as e:
            logger.exception("reactor %s receive error from %s", reactor.name, peer.id[:8])
            await self.stop_peer_for_error(peer, str(e))
