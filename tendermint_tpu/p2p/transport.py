"""TCP MultiplexTransport: listen/dial, upgrading raw conns through
SecretConnection → NodeInfo handshake → MConnection-backed Peer
(reference p2p/transport.go:138,193,405,535; p2p/peer.go:23).

The Peer surface is identical to the in-proc transport's, so every reactor
works unchanged over real sockets.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, List, Optional

from ..libs import protowire as pw
from .base import ChannelDescriptor, Peer
from .conn.mconnection import MConnConfig, MConnection
from .conn.secret_connection import SecretConnection
from .key import NodeKey, pubkey_to_id
from .netaddress import NetAddress
from .node_info import NodeInfo, NodeInfoError

logger = logging.getLogger("tmtpu.p2p.tcp")

HANDSHAKE_TIMEOUT = 20.0
DIAL_TIMEOUT = 3.0


class TransportError(Exception):
    pass


class TCPPeer(Peer):
    """A peer over an MConnection on a SecretConnection (p2p/peer.go)."""

    def __init__(self, node_info: NodeInfo, mconn_factory, outbound: bool,
                 persistent: bool = False, socket_addr: Optional[NetAddress] = None):
        super().__init__(node_info.node_id, outbound, persistent)
        self.node_info = node_info
        self.socket_addr = socket_addr
        self._mconn: MConnection = mconn_factory(self._on_receive, self._on_error)
        self._switch = None
        self._running = False

    def bind(self, switch) -> None:
        self._switch = switch

    def start(self) -> None:
        self._running = True
        self._mconn.start()

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self.try_send(channel_id, msg)

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        if not self._running:
            return False
        return self._mconn.try_send(channel_id, msg)

    async def send_wait(self, channel_id: int, msg: bytes) -> bool:
        if not self._running:
            return False
        return await self._mconn.send(channel_id, msg)

    def is_running(self) -> bool:
        return self._running

    async def stop(self) -> None:
        self._running = False
        await self._mconn.stop()

    async def _on_receive(self, channel_id: int, msg: bytes) -> None:
        if self._switch is not None:
            await self._switch.dispatch(channel_id, self, msg)

    async def _on_error(self, err: Exception) -> None:
        self._running = False
        if self._switch is not None:
            await self._switch.stop_peer_for_error(self, f"conn error: {err}")


class TCPTransport:
    """(p2p/transport.go MultiplexTransport)"""

    def __init__(self, node_key: NodeKey, node_info: NodeInfo,
                 chan_descs: List[ChannelDescriptor],
                 mconn_config: Optional[MConnConfig] = None):
        self.node_key = node_key
        self.node_info = node_info
        self.chan_descs = chan_descs
        self.mconn_config = mconn_config or MConnConfig()
        self._server: Optional[asyncio.base_events.Server] = None
        self.listen_addr: Optional[NetAddress] = None
        self._on_inbound: Optional[Callable] = None

    # -- listening -----------------------------------------------------------

    async def listen(self, host: str, port: int, on_inbound) -> NetAddress:
        """Start accepting; on_inbound(TCPPeer) is called per upgraded conn."""
        self._on_inbound = on_inbound
        self._server = await asyncio.start_server(self._accept, host, port)
        actual_port = self._server.sockets[0].getsockname()[1]
        self.listen_addr = NetAddress(self.node_key.id, host, actual_port)
        self.node_info.listen_addr = f"tcp://{host}:{actual_port}"
        logger.info("p2p listening on %s", self.listen_addr)
        return self.listen_addr

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            peer = await asyncio.wait_for(
                self._upgrade(reader, writer, outbound=False,
                              expected_id=None),
                HANDSHAKE_TIMEOUT)
        except Exception as e:
            logger.debug("inbound upgrade failed: %s", e)
            writer.close()
            return
        if self._on_inbound is not None:
            await self._on_inbound(peer)

    # -- dialing -------------------------------------------------------------

    async def dial(self, addr: NetAddress, persistent: bool = False) -> TCPPeer:
        """(transport.go Dial) TCP connect + upgrade + ID verification."""
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(addr.host, addr.port), DIAL_TIMEOUT)
        try:
            peer = await asyncio.wait_for(
                self._upgrade(reader, writer, outbound=True,
                              expected_id=addr.id),
                HANDSHAKE_TIMEOUT)
        except Exception:
            writer.close()
            raise
        peer.persistent = persistent
        peer.socket_addr = addr
        return peer

    # -- the upgrade path (transport.go:405 upgrade, :535 handshake) ---------

    async def _upgrade(self, reader, writer, outbound: bool,
                       expected_id: Optional[str]) -> TCPPeer:
        sc = await SecretConnection.make(reader, writer, self.node_key.priv_key)
        conn_id = pubkey_to_id(sc.remote_pubkey)
        if expected_id is not None and conn_id != expected_id:
            raise TransportError(
                f"dialed {expected_id[:12]} but connected to {conn_id[:12]}")

        # NodeInfo exchange over the encrypted conn (both directions async
        # like the reference's cmn.Parallel)
        await sc.write_msg(self.node_info.encode())
        raw = await asyncio.wait_for(sc.read_msg(max_size=10240), HANDSHAKE_TIMEOUT)
        ln, pos = pw.decode_varint(raw, 0)
        rem_info = NodeInfo.decode(raw[pos:pos + ln])
        rem_info.validate_basic()
        if rem_info.node_id != conn_id:
            raise TransportError("node info ID does not match secret-conn pubkey")
        self.node_info.compatible_with(rem_info)

        def mconn_factory(on_receive, on_error):
            return MConnection(sc, self.chan_descs, on_receive, on_error,
                               self.mconn_config)

        return TCPPeer(rem_info, mconn_factory, outbound)
