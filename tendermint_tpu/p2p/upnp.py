"""UPnP NAT traversal (reference p2p/upnp/{upnp,probe}.go).

NAT seam: ``discover()`` finds an Internet Gateway Device via SSDP
multicast, resolves its WAN(IP|PPP)Connection control URL from the root
description XML, and returns a :class:`UPnPNAT` speaking the three SOAP
actions the reference uses — GetExternalIPAddress, AddPortMapping,
DeletePortMapping (upnp.go:301,347,384). ``probe()`` mirrors probe.go's
capability check: map a port, report external address, unmap.

Stdlib only (socket + urllib + ElementTree). Everything network-y takes an
injectable endpoint so tests run against an in-proc fake IGD — real
gateways obviously don't exist in CI. The node treats UPnP as best-effort:
any failure here degrades to manual port forwarding, never to a crash
(cmd start's laddr binding does not depend on it).
"""

from __future__ import annotations

import socket
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional, Tuple
from urllib.parse import urljoin, urlparse

SSDP_ADDR = ("239.255.255.250", 1900)
_SEARCH_TARGET = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
_WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


def _msearch(timeout: float, ssdp_addr) -> Optional[str]:
    """One SSDP M-SEARCH round; returns the LOCATION header or None."""
    msg = ("M-SEARCH * HTTP/1.1\r\n"
           f"HOST: {ssdp_addr[0]}:{ssdp_addr[1]}\r\n"
           'MAN: "ssdp:discover"\r\n'
           f"ST: {_SEARCH_TARGET}\r\n"
           "MX: 2\r\n\r\n").encode()
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(msg, ssdp_addr)
        try:
            data, _peer = s.recvfrom(4096)
        except socket.timeout:
            return None
    for line in data.decode(errors="replace").split("\r\n"):
        k, _, v = line.partition(":")
        if k.strip().lower() == "location":
            return v.strip()
    return None


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find_control_url(desc_xml: bytes, base_url: str) -> Tuple[str, str]:
    """Walk the device tree for a WAN(IP|PPP)Connection service
    (upnp.go:159 getChildDevice / :169 getChildService)."""
    root = ET.fromstring(desc_xml)
    for svc in root.iter():
        if _strip_ns(svc.tag) != "service":
            continue
        stype = ctrl = ""
        for child in svc:
            if _strip_ns(child.tag) == "serviceType":
                stype = (child.text or "").strip()
            elif _strip_ns(child.tag) == "controlURL":
                ctrl = (child.text or "").strip()
        if stype in _WAN_SERVICES and ctrl:
            return urljoin(base_url, ctrl), stype
    raise UPnPError("no WANIPConnection/WANPPPConnection service found")


def _soap_call(control_url: str, service_type: str, action: str,
               args: dict, timeout: float = 5.0) -> ET.Element:
    body = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
    envelope = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{service_type}">{body}</u:{action}>'
        "</s:Body></s:Envelope>").encode()
    req = urllib.request.Request(control_url, data=envelope, headers={
        "Content-Type": 'text/xml; charset="utf-8"',
        "SOAPAction": f'"{service_type}#{action}"',
    })
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return ET.fromstring(resp.read())
    except urllib.error.HTTPError as e:
        raise UPnPError(f"{action} failed: HTTP {e.code}") from None
    except Exception as e:
        raise UPnPError(f"{action} failed: {e}") from None


def _local_ipv4(gateway_host: str) -> str:
    """The local address a packet to the gateway would use (upnp.go:179)."""
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.connect((gateway_host, 1900))
        return s.getsockname()[0]


@dataclass
class UPnPNAT:
    """The reference's NAT interface (upnp.go:29)."""

    control_url: str
    service_type: str

    def get_external_address(self) -> str:
        doc = _soap_call(self.control_url, self.service_type,
                         "GetExternalIPAddress", {})
        for el in doc.iter():
            if _strip_ns(el.tag) == "NewExternalIPAddress":
                if not el.text:
                    raise UPnPError("gateway returned empty external IP")
                return el.text.strip()
        raise UPnPError("no NewExternalIPAddress in response")

    def add_port_mapping(self, protocol: str, external_port: int,
                         internal_port: int, description: str,
                         lease_seconds: int = 0) -> int:
        host = urlparse(self.control_url).hostname or ""
        _soap_call(self.control_url, self.service_type, "AddPortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": external_port,
            "NewProtocol": protocol.upper(),
            "NewInternalPort": internal_port,
            "NewInternalClient": _local_ipv4(host),
            "NewEnabled": 1,
            "NewPortMappingDescription": description,
            "NewLeaseDuration": lease_seconds,
        })
        return external_port

    def delete_port_mapping(self, protocol: str, external_port: int) -> None:
        _soap_call(self.control_url, self.service_type, "DeletePortMapping", {
            "NewRemoteHost": "",
            "NewExternalPort": external_port,
            "NewProtocol": protocol.upper(),
        })


def discover(timeout: float = 3.0, ssdp_addr=SSDP_ADDR,
             attempts: int = 2) -> UPnPNAT:
    """(upnp.go:39 Discover) SSDP -> description fetch -> control URL."""
    location = None
    for _ in range(attempts):
        location = _msearch(timeout, ssdp_addr)
        if location:
            break
    if not location:
        raise UPnPError("no UPnP gateway answered the SSDP search")
    try:
        with urllib.request.urlopen(location, timeout=timeout) as resp:
            desc = resp.read()
    except Exception as e:
        raise UPnPError(f"could not fetch device description: {e}") from None
    control_url, service_type = _find_control_url(desc, location)
    return UPnPNAT(control_url=control_url, service_type=service_type)


def probe(int_port: int = 26656, ext_port: int = 26656,
          timeout: float = 3.0, ssdp_addr=SSDP_ADDR) -> dict:
    """(probe.go:90 Probe) capability check: discover, map, read external
    address, unmap. Returns {external_ip, port_mapping} — hairpin testing
    needs a second vantage point and is out of scope, like the reference's
    testHairpin which requires a live dial-back."""
    nat = discover(timeout=timeout, ssdp_addr=ssdp_addr)
    caps = {"external_ip": None, "port_mapping": False}
    try:
        caps["external_ip"] = nat.get_external_address()
    except UPnPError:
        pass
    try:
        nat.add_port_mapping("tcp", ext_port, int_port, "tendermint-tpu probe",
                             lease_seconds=60)
        caps["port_mapping"] = True
        nat.delete_port_mapping("tcp", ext_port)
    except UPnPError:
        pass
    return caps
