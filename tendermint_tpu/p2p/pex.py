"""PEX (peer exchange) reactor + address book
(reference p2p/pex/pex_reactor.go:24,84 and p2p/pex/addrbook.go).

Channel 0x00. Wire messages are the reference's proto oneof
(proto/tendermint/p2p/pex.proto): PexRequest=1, PexAddrs=2{addrs}.
Each addr: NetAddress{id=1, ip=2, port=3}.

The address book keeps new/old buckets (addresses graduate to "old" after a
successful connection), persists to JSON, and answers random selections
biased toward old (proven) addresses — the reference's GetSelection shape
without its 256-bucket hashing (bucket pressure only matters at
internet-crawl scale; the eviction policy is preserved).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..libs import protowire as pw
from .base import ChannelDescriptor, Peer, Reactor
from .netaddress import NetAddress

logger = logging.getLogger("tmtpu.p2p.pex")

PEX_CHANNEL = 0x00
REQUEST_INTERVAL = 30.0       # min seconds between requests per peer
MAX_ADDRS_PER_MSG = 100
NEW_BUCKET_CAP = 1024
OLD_BUCKET_CAP = 1024


# -- wire --------------------------------------------------------------------

def encode_pex_request() -> bytes:
    w = pw.Writer()
    w.message(1, b"")
    return w.finish()


def encode_pex_addrs(addrs: List[NetAddress]) -> bytes:
    inner = pw.Writer()
    for a in addrs:
        aw = pw.Writer()
        aw.string(1, a.id)
        aw.string(2, a.host)
        aw.varint(3, a.port)
        inner.message(1, aw.finish())
    w = pw.Writer()
    w.message(2, inner.finish())
    return w.finish()


def decode_pex_msg(data: bytes):
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            return "request", None
        if fn == 2:
            addrs = []
            for afn, _awt, av in pw.iter_fields(pw.as_bytes(v)):
                if afn != 1:
                    continue
                f = pw.fields_dict(pw.as_bytes(av))
                try:
                    addrs.append(NetAddress(
                        (f.get(1, [b""])[0] or b"").decode(),
                        (f.get(2, [b""])[0] or b"").decode(),
                        int(f.get(3, [0])[0] or 0)))
                except Exception:
                    continue
            return "addrs", addrs
    raise ValueError("empty pex message")


# -- address book ------------------------------------------------------------

@dataclass
class _KnownAddress:
    addr: NetAddress
    src_id: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket: str = "new"  # new | old


class AddrBook:
    """(p2p/pex/addrbook.go AddrBook)

    ``scoreboard`` (a libs.peerscore.PeerScoreboard, optional) ties the
    book into the sync planes' shared ban ledger: ``mark_bad`` strikes it
    severely, and ``pick_address``/``get_selection`` exclude banned /
    backing-off peers — so PEX can't keep redialing (or advertising) a
    peer blocksync already severe-banned."""

    def __init__(self, file_path: str = "", strict: bool = True,
                 scoreboard=None):
        self.file_path = file_path
        self.strict = strict
        self.scoreboard = scoreboard
        self._addrs: Dict[str, _KnownAddress] = {}
        self._our_ids: set = set()
        if file_path and os.path.exists(file_path):
            self._load()

    def add_our_address(self, node_id: str) -> None:
        self._our_ids.add(node_id)

    def add_address(self, addr: NetAddress, src_id: str = "") -> bool:
        """(addrbook.go AddAddress) returns True if newly added."""
        if addr.id in self._our_ids:
            return False
        if self.strict and not _routable(addr):
            return False
        known = self._addrs.get(addr.id)
        if known is not None:
            return False
        if sum(1 for k in self._addrs.values() if k.bucket == "new") \
                >= NEW_BUCKET_CAP:
            self._evict_new()
        self._addrs[addr.id] = _KnownAddress(addr, src_id)
        return True

    def _evict_new(self) -> None:
        # drop the most-failed never-succeeded address (addrbook eviction)
        cands = [k for k in self._addrs.values() if k.bucket == "new"]
        if cands:
            victim = max(cands, key=lambda k: (k.attempts, -k.last_attempt))
            del self._addrs[victim.addr.id]

    def mark_attempt(self, addr: NetAddress) -> None:
        k = self._addrs.get(addr.id)
        if k is not None:
            k.attempts += 1
            k.last_attempt = time.time()

    def mark_good(self, node_id: str) -> None:
        """(addrbook.go MarkGood) graduate to the old bucket."""
        k = self._addrs.get(node_id)
        if k is not None:
            k.attempts = 0
            k.last_success = time.time()
            k.bucket = "old"

    def mark_bad(self, node_id: str, reason: str = "addrbook") -> None:
        """Drop the address AND strike the shared scoreboard (severe: the
        caller has decided this peer is bad, not merely slow) so the sync
        planes and PEX agree the peer is off-limits."""
        self._addrs.pop(node_id, None)
        if self.scoreboard is not None:
            self.scoreboard.record_failure(node_id, reason, severe=True)

    def _usable(self, node_id: str) -> bool:
        """Scoreboard gate for handing out / dialing an address: banned or
        backing-off peers are excluded (blocksync/statesync verdicts bind
        PEX too)."""
        sb = self.scoreboard
        return sb is None or not (sb.banned(node_id) or sb.in_backoff(node_id))

    def size(self) -> int:
        return len(self._addrs)

    def has(self, node_id: str) -> bool:
        return node_id in self._addrs

    def get_selection(self, limit: int = MAX_ADDRS_PER_MSG) -> List[NetAddress]:
        """Random sample biased toward proven (old-bucket) addresses
        (addrbook.go GetSelectionWithBias shape); scoreboard-banned /
        backing-off peers are never advertised."""
        old = [k.addr for k in self._addrs.values()
               if k.bucket == "old" and self._usable(k.addr.id)]
        new = [k.addr for k in self._addrs.values()
               if k.bucket == "new" and self._usable(k.addr.id)]
        random.shuffle(old)
        random.shuffle(new)
        take_old = min(len(old), -(-limit * 2 // 3))  # ceil: bias to old
        out = old[:take_old] + new[:limit - take_old]
        return out[:limit]

    def pick_address(self, exclude=()) -> Optional[NetAddress]:
        """A random dialable address, preferring fewer failed attempts;
        ``exclude`` filters already-connected/self ids BEFORE pooling (a
        stable sort over unusable entries must not starve fresh ones), and
        scoreboard-banned / backing-off peers are filtered the same way."""
        cands = sorted((k for k in self._addrs.values()
                        if k.addr.id not in exclude
                        and self._usable(k.addr.id)),
                       key=lambda k: k.attempts)
        if not cands:
            return None
        pool = cands[:max(1, len(cands) // 2)]
        return random.choice(pool).addr

    # -- persistence (addrbook.go saveToFile/loadFromFile) -------------------

    def save(self) -> None:
        if not self.file_path:
            return
        doc = {"addrs": [
            {"id": k.addr.id, "host": k.addr.host, "port": k.addr.port,
             "src": k.src_id, "attempts": k.attempts, "bucket": k.bucket,
             "last_success": k.last_success}
            for k in self._addrs.values()
        ]}
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        tmp = self.file_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.file_path)

    def _load(self) -> None:
        """A corrupted/truncated book file loads as EMPTY with a warning —
        never a crash at node start (the book is a cache, the net refills
        it), and never a half-parsed book (entries staged, committed only
        when the whole document decodes)."""
        try:
            with open(self.file_path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(doc).__name__}")
            staged: Dict[str, _KnownAddress] = {}
            for a in doc.get("addrs", []):
                k = _KnownAddress(NetAddress(a["id"], a["host"], a["port"]),
                                  a.get("src", ""), a.get("attempts", 0),
                                  bucket=a.get("bucket", "new"),
                                  last_success=a.get("last_success", 0.0))
                staged[k.addr.id] = k
            self._addrs.update(staged)
        except Exception as e:
            logger.warning("addrbook %s unreadable (%s); starting with an "
                           "empty book", self.file_path, e)


def _routable(addr: NetAddress) -> bool:
    # strict mode refuses obviously-unroutable junk; localhost allowed for
    # localnets (the reference gates this by addrBookStrict=false in tests)
    if not addr.host or not 0 < addr.port < 65536:
        return False
    if addr.host in ("0.0.0.0", "::", "255.255.255.255"):
        return False
    return True


# -- reactor ------------------------------------------------------------------

class PEXReactor(Reactor):
    """(pex_reactor.go) requests addresses from peers when below the target
    outbound count and dials book addresses; serves selections on request."""

    def __init__(self, book: AddrBook, target_outbound: int = 10,
                 ensure_interval: float = 5.0,
                 request_interval: float = REQUEST_INTERVAL,
                 seed_mode: bool = False,
                 seed_disconnect_wait: float = 3.0,
                 crawl_interval: float = 30.0):
        super().__init__("PEX")
        self.book = book
        self.target_outbound = target_outbound
        self.ensure_interval = ensure_interval
        # both the flood defense AND our own outgoing request pacing
        # (pex_reactor.go ensurePeers + receiveRequest share the interval)
        self.request_interval = request_interval
        # seed mode (pex_reactor.go seed branch): crawl the book to keep it
        # fresh; serve inbound peers one selection then hang up
        self.seed_mode = seed_mode
        self.seed_disconnect_wait = seed_disconnect_wait
        self.crawl_interval = crawl_interval
        self._last_request: Dict[str, float] = {}   # inbound, per peer
        self._last_sent: Dict[str, float] = {}      # outgoing, per peer
        self._requested: set = set()
        self._task: Optional[asyncio.Task] = None
        self._crawl_task: Optional[asyncio.Task] = None
        # strong refs: the loop holds only weak refs to tasks, and a
        # GC-collected disconnect task would leave a served peer connected
        self._bg_tasks: set = set()

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10,
                                  recv_message_capacity=64 * 1024)]

    async def start(self) -> None:
        if self.seed_mode:
            if self._crawl_task is None:
                self._crawl_task = asyncio.create_task(self._crawl_routine())
        elif self._task is None:
            self._task = asyncio.create_task(self._ensure_peers_routine())

    async def stop(self) -> None:
        for attr in ("_task", "_crawl_task"):
            t = getattr(self, attr)
            if t is not None:
                t.cancel()
                setattr(self, attr, None)
        self.book.save()

    async def add_peer(self, peer: Peer) -> None:
        # learn the peer's self-reported listen addr
        info = getattr(peer, "node_info", None)
        if info is not None and info.listen_addr:
            try:
                hostport = info.listen_addr.split("://", 1)[-1]
                host, _, port = hostport.rpartition(":")
                sock = getattr(peer, "socket_addr", None)
                host = getattr(sock, "host", None) or host
                self.book.add_address(NetAddress(peer.id, host, int(port)),
                                      src_id=peer.id)
            except Exception:
                pass
        self.book.mark_good(peer.id)

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        kind, payload = decode_pex_msg(msg_bytes)
        if kind == "request":
            now = time.monotonic()
            # accept at interval/3 (pex_reactor.go receiveRequest): a margin
            # below peers' send pacing so clock jitter never drops them
            if peer.id in self._last_request and \
                    now - self._last_request[peer.id] < self.request_interval / 3:
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(
                        peer, "pex request flood")
                return
            self._last_request[peer.id] = now
            peer.try_send(PEX_CHANNEL,
                          encode_pex_addrs(self.book.get_selection()))
            if self.seed_mode:
                # seeds answer one request then hang up (pex_reactor.go
                # receiveRequest seed branch): they hand out addresses,
                # they don't hold connections
                t = asyncio.create_task(self._disconnect_later(peer))
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_tasks.discard)
        else:  # addrs
            if peer.id not in self._requested:
                # unsolicited address dump (pex_reactor.go ReceiveAddrs err)
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(
                        peer, "unsolicited pex addrs")
                return
            self._requested.discard(peer.id)
            for addr in payload[:MAX_ADDRS_PER_MSG]:
                self.book.add_address(addr, src_id=peer.id)

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self._last_request.pop(peer.id, None)
        self._requested.discard(peer.id)

    async def _disconnect_later(self, peer: Peer) -> None:
        try:
            await asyncio.sleep(self.seed_disconnect_wait)
            if self.switch is not None:
                await self.switch.stop_peer_gracefully(peer)
        except Exception:
            pass

    # -- seed crawler (pex_reactor.go crawlPeersRoutine) --------------------

    async def _crawl_routine(self) -> None:
        try:
            while True:
                await self._crawl_once()
                await asyncio.sleep(self.crawl_interval)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("pex crawler died")

    async def _crawl_once(self) -> None:
        """Dial a few book addresses, request their addresses, hang up
        shortly after (crawlPeers): keeps the book fresh and prunes dead
        entries via mark_attempt accounting."""
        if self.switch is None:
            return
        exclude = set(self.switch.peers) | {self.switch.node_id}
        for _ in range(3):
            addr = self.book.pick_address(exclude)
            if addr is None:
                return
            exclude.add(addr.id)
            self.book.mark_attempt(addr)
            ok = await self.switch.dial_peer(addr)
            if not ok:
                continue
            self.book.mark_good(addr.id)
            peer = self.switch.peers.get(addr.id)
            if peer is None:
                continue
            self._requested.add(peer.id)
            peer.try_send(PEX_CHANNEL, encode_pex_request())
            await asyncio.sleep(self.seed_disconnect_wait)
            await self.switch.stop_peer_gracefully(peer)

    # -- the ensure-peers loop (pex_reactor.go ensurePeersRoutine) ----------

    async def _ensure_peers_routine(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.ensure_interval)
                await self._ensure_peers()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("pex ensure-peers died")

    async def _ensure_peers(self) -> None:
        if self.switch is None:
            return
        out = sum(1 for p in self.switch.peers.values() if p.outbound)
        need = self.target_outbound - out
        if need <= 0:
            return
        # ask a random connected peer for more addresses (paced per peer so
        # we never trip the remote's flood defense)
        now = time.monotonic()
        cands = [p for p in self.switch.peers.values()
                 if now - self._last_sent.get(p.id, -1e9) >= self.request_interval]
        if cands:
            p = random.choice(cands)
            self._last_sent[p.id] = now
            self._requested.add(p.id)
            p.try_send(PEX_CHANNEL, encode_pex_request())
        # dial from the book
        exclude = set(self.switch.peers) | {self.switch.node_id}
        for _ in range(need):
            addr = self.book.pick_address(exclude)
            if addr is None:
                break
            exclude.add(addr.id)
            self.book.mark_attempt(addr)
            ok = await self.switch.dial_peer(addr)
            if ok:
                self.book.mark_good(addr.id)
