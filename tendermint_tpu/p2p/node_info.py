"""NodeInfo: the post-encryption handshake payload
(reference p2p/node_info.go DefaultNodeInfo + CompatibleWith).

Carries protocol versions, node ID, listen address, network (chain id),
software version, advertised channels, and moniker. Exchanged as a
length-delimited protobuf right after SecretConnection establishment
(p2p/transport.go:535 handshake); peers are rejected on network or
block-protocol mismatch, missing common channels, or ID spoofing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from ..libs import protowire as pw

P2P_PROTOCOL = 8      # reference version/version.go:16 P2PProtocol
BLOCK_PROTOCOL = 11   # reference version/version.go:22 BlockProtocol
SOFTWARE_VERSION = "tendermint-tpu/0.1.0"


class NodeInfoError(Exception):
    pass


@dataclass
class NodeInfo:
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""
    version: str = SOFTWARE_VERSION
    channels: bytes = b""
    moniker: str = "anonymous"
    protocol_p2p: int = P2P_PROTOCOL
    protocol_block: int = BLOCK_PROTOCOL
    protocol_app: int = 0
    rpc_address: str = ""
    tx_index: str = "on"

    def encode(self) -> bytes:
        """Length-delimited DefaultNodeInfo (proto/tendermint/p2p/types.proto)."""
        ver = pw.Writer()
        ver.varint(1, self.protocol_p2p)
        ver.varint(2, self.protocol_block)
        ver.varint(3, self.protocol_app)
        other = pw.Writer()
        other.string(1, self.tx_index)
        other.string(2, self.rpc_address)
        w = pw.Writer()
        w.message(1, ver.finish())
        w.string(2, self.node_id)
        w.string(3, self.listen_addr)
        w.string(4, self.network)
        w.string(5, self.version)
        w.bytes(6, self.channels)
        w.string(7, self.moniker)
        w.message(8, other.finish())
        return pw.length_delimited(w.finish())

    @classmethod
    def decode(cls, body: bytes) -> "NodeInfo":
        f = pw.fields_dict(body)
        info = cls()
        if 1 in f:
            vf = pw.fields_dict(f[1][0])
            info.protocol_p2p = vf.get(1, [0])[0]
            info.protocol_block = vf.get(2, [0])[0]
            info.protocol_app = vf.get(3, [0])[0]
        info.node_id = f.get(2, [b""])[0].decode()
        info.listen_addr = f.get(3, [b""])[0].decode()
        info.network = f.get(4, [b""])[0].decode()
        info.version = f.get(5, [b""])[0].decode()
        info.channels = f.get(6, [b""])[0]
        info.moniker = f.get(7, [b""])[0].decode()
        if 8 in f:
            of = pw.fields_dict(f[8][0])
            info.tx_index = of.get(1, [b""])[0].decode()
            info.rpc_address = of.get(2, [b""])[0].decode()
        return info

    def validate_basic(self) -> None:
        if not self.node_id:
            raise NodeInfoError("empty node id")
        if len(self.channels) > 64:
            raise NodeInfoError("too many channels")

    def compatible_with(self, other: "NodeInfo") -> None:
        """(p2p/node_info.go CompatibleWith)"""
        if self.protocol_block != other.protocol_block:
            raise NodeInfoError(
                f"block protocol mismatch: {self.protocol_block} vs "
                f"{other.protocol_block}")
        if self.network != other.network:
            raise NodeInfoError(
                f"network mismatch: {self.network!r} vs {other.network!r}")
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise NodeInfoError("no common channels")
