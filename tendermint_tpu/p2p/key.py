"""Node identity key (reference p2p/key.go).

A persistent ed25519 keypair; the node ID is the lowercase hex of the
pubkey's address (SHA256-20), exactly the reference's ``PubKeyToID``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from ..crypto import Ed25519PrivKey, PrivKey


def pubkey_to_id(pub) -> str:
    """(p2p/key.go PubKeyToID)"""
    return pub.address().hex()


@dataclass
class NodeKey:
    priv_key: PrivKey

    @property
    def id(self) -> str:
        return pubkey_to_id(self.priv_key.pub_key())

    def pub_key(self):
        return self.priv_key.pub_key()

    def save_as(self, path: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = {"priv_key": {"type": "tendermint/PrivKeyEd25519",
                            "value": self.priv_key.bytes().hex()}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            doc = json.load(f)
        return cls(Ed25519PrivKey(bytes.fromhex(doc["priv_key"]["value"])))

    @classmethod
    def load_or_gen(cls, path: str, seed: bytes = None) -> "NodeKey":
        """(p2p/key.go LoadOrGenNodeKey)"""
        if os.path.exists(path):
            return cls.load(path)
        nk = cls(Ed25519PrivKey.generate(seed))
        nk.save_as(path)
        return nk
