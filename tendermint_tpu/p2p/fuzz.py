"""FuzzedConnection: fault-injecting connection wrapper
(reference p2p/fuzz.go:14, config/config.go:663 FuzzConnConfig).

Wraps a SecretConnection-shaped object and probabilistically drops or
delays reads/writes — the runtime fault-injection half of the QA story
(the e2e perturbations being the process-level half).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass


@dataclass
class FuzzConnConfig:
    """(config.go:663 DefaultFuzzConnConfig)"""

    mode: str = "drop"        # "drop" | "delay"
    prob_drop_rw: float = 0.2
    prob_drop_conn: float = 0.0
    max_delay_s: float = 3.0
    seed: int = 0


class FuzzedConnection:
    """Duck-types the SecretConnection surface used by MConnection."""

    def __init__(self, conn, config: FuzzConnConfig = None):
        self.conn = conn
        self.config = config or FuzzConnConfig()
        self._rng = random.Random(self.config.seed or None)
        self.dropped_reads = 0
        self.dropped_writes = 0

    async def _fuzz(self) -> bool:
        """True = drop this operation."""
        cfg = self.config
        if cfg.mode == "drop":
            if cfg.prob_drop_conn and self._rng.random() < cfg.prob_drop_conn:
                self.close()
                raise ConnectionError("fuzzed connection dropped")
            return self._rng.random() < cfg.prob_drop_rw
        if cfg.mode == "delay":
            await asyncio.sleep(self._rng.random() * cfg.max_delay_s)
        return False

    async def write(self, data: bytes) -> None:
        if await self._fuzz():
            self.dropped_writes += 1
            return  # silently dropped (fuzz.go Write)
        await self.conn.write(data)

    async def read(self) -> bytes:
        while await self._fuzz():
            self.dropped_reads += 1
            await self.conn.read()  # consume and discard (fuzz.go Read)
        return await self.conn.read()

    async def read_exactly(self, n: int) -> bytes:
        return await self.conn.read_exactly(n)

    async def read_msg(self, max_size: int = 10 * 1024 * 1024) -> bytes:
        return await self.conn.read_msg(max_size)

    async def write_msg(self, framed: bytes) -> None:
        if await self._fuzz():
            self.dropped_writes += 1
            return
        await self.conn.write_msg(framed)

    def close(self) -> None:
        if hasattr(self.conn, "close"):
            self.conn.close()

    @property
    def remote_pubkey(self):
        return getattr(self.conn, "remote_pubkey", None)
