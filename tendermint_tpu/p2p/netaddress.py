"""ID@host:port network addresses (reference p2p/netaddress.go)."""

from __future__ import annotations

from dataclasses import dataclass


class AddressError(Exception):
    pass


@dataclass(frozen=True)
class NetAddress:
    id: str
    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.id}@{self.host}:{self.port}"

    @classmethod
    def parse(cls, addr: str) -> "NetAddress":
        """Accepts id@host:port (id mandatory, reference NewNetAddressString)."""
        if "@" not in addr:
            raise AddressError(f"address {addr!r} missing node id")
        node_id, hostport = addr.split("@", 1)
        node_id = node_id.lower()
        if len(node_id) != 40 or any(c not in "0123456789abcdef" for c in node_id):
            raise AddressError(f"invalid node id {node_id!r}")
        if ":" not in hostport:
            raise AddressError(f"address {addr!r} missing port")
        host, port_s = hostport.rsplit(":", 1)
        try:
            port = int(port_s)
        except ValueError:
            raise AddressError(f"bad port in {addr!r}")
        if not 0 < port < 65536:
            raise AddressError(f"port out of range in {addr!r}")
        return cls(node_id, host or "127.0.0.1", port)


def parse_peer_list(s: str) -> list:
    """Comma-separated id@host:port list (config p2p.persistent_peers)."""
    return [NetAddress.parse(p.strip()) for p in s.split(",") if p.strip()]
