"""Authenticated encrypted transport + channel multiplexing
(reference p2p/conn/: secret_connection.go, connection.go)."""

from .secret_connection import SecretConnection  # noqa: F401
from .mconnection import MConnection, MConnConfig  # noqa: F401
