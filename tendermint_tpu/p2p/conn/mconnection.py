"""MConnection: multiplexes N logical channels over one SecretConnection
(reference p2p/conn/connection.go:78).

Wire format mirrors the reference: length-delimited protobuf ``Packet``
oneof — PacketPing(field 1), PacketPong(field 2), PacketMsg(field 3:
channel_id=1, eof=2, data=3) — with messages split into packets of
``max_packet_msg_payload_size`` bytes (connection.go:27-34).

Scheduling mirrors sendSomePacketMsgs/sendPacketMsg (connection.go:504,520):
the next packet comes from the channel with the least
``recently_sent / priority`` ratio, with recently_sent decayed every flush.
Rate limiting is a token bucket over sealed bytes (libs/flowrate analog);
ping/pong keepalive with a pong timeout tears the connection down.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional

from ...libs import protowire as pw
from ...libs.faults import faults
from ..base import ChannelDescriptor

logger = logging.getLogger("tmtpu.p2p.mconn")


@dataclass
class MConnConfig:
    """(connection.go:122 MConnConfig)"""

    send_rate: int = 5_120_000          # bytes/s
    recv_rate: int = 5_120_000
    max_packet_msg_payload_size: int = 1024
    flush_throttle: float = 0.1
    ping_interval: float = 60.0
    pong_timeout: float = 45.0


def _encode_packet_msg(channel_id: int, eof: bool, data: bytes) -> bytes:
    inner = pw.Writer()
    inner.varint(1, channel_id)
    if eof:
        inner.bool(2, True)
    if data:
        inner.bytes(3, data)
    w = pw.Writer()
    w.message(3, inner.finish())
    return pw.length_delimited(w.finish())


def _encode_ping() -> bytes:
    w = pw.Writer()
    w.message(1, b"")
    return pw.length_delimited(w.finish())


def _encode_pong() -> bytes:
    w = pw.Writer()
    w.message(2, b"")
    return pw.length_delimited(w.finish())


class _Channel:
    def __init__(self, desc: ChannelDescriptor, max_payload: int):
        self.desc = desc
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=max(1, desc.send_queue_capacity))
        self.sending: bytes = b""
        self.recently_sent = 0
        self.recving = b""
        self.max_payload = max_payload

    def next_packet(self) -> Optional[bytes]:
        """The next PacketMsg for this channel, or None if idle."""
        if not self.sending:
            if self.queue.empty():
                return None
            self.sending = self.queue.get_nowait()
        chunk = self.sending[: self.max_payload]
        rest = self.sending[self.max_payload:]
        self.sending = rest
        eof = not rest
        self.recently_sent += len(chunk)
        return _encode_packet_msg(self.desc.id, eof, chunk)

    def has_data(self) -> bool:
        return bool(self.sending) or not self.queue.empty()


# process-wide p2p byte counters (p2p/metrics.go): set once by the node;
# None (tests, tools) is a no-op
_p2p_metrics = None


def set_p2p_metrics(m) -> None:
    global _p2p_metrics
    _p2p_metrics = m


class MConnection:
    def __init__(self, conn, chan_descs: List[ChannelDescriptor],
                 on_receive: Callable[[int, bytes], Awaitable[None]],
                 on_error: Callable[[Exception], Awaitable[None]],
                 config: Optional[MConnConfig] = None):
        self.conn = conn  # SecretConnection or any object with read()/write()
        self.config = config or MConnConfig()
        self.channels: Dict[int, _Channel] = {
            d.id: _Channel(d, self.config.max_packet_msg_payload_size)
            for d in chan_descs
        }
        self.on_receive = on_receive
        self.on_error = on_error
        self._send_task: Optional[asyncio.Task] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._ping_task: Optional[asyncio.Task] = None
        self._send_event = asyncio.Event()
        self._pong_pending = False
        self._pong_deadline = 0.0
        self._raw_sends: set = set()
        self._send_budget = float(self.config.send_rate)
        self._budget_at = time.monotonic()
        self._recv_budget = float(self.config.recv_rate)
        self._recv_budget_at = time.monotonic()
        self._stopped = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._send_task = asyncio.create_task(self._send_routine())
        self._recv_task = asyncio.create_task(self._recv_routine())
        self._ping_task = asyncio.create_task(self._ping_routine())

    async def stop(self) -> None:
        self._stopped = True
        for t in (self._send_task, self._recv_task, self._ping_task):
            if t is not None:
                t.cancel()
        for t in (self._send_task, self._recv_task, self._ping_task):
            if t is not None:
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        if hasattr(self.conn, "close"):
            self.conn.close()

    # -- sending -------------------------------------------------------------

    async def send(self, channel_id: int, msg: bytes, timeout: float = 10.0) -> bool:
        """Blocking send with the reference's 10s default timeout."""
        ch = self.channels.get(channel_id)
        if ch is None or self._stopped:
            return False
        if faults.armed("net.corrupt"):
            msg = faults.mutate("net.corrupt", msg)
        try:
            await asyncio.wait_for(ch.queue.put(msg), timeout)
        except asyncio.TimeoutError:
            return False
        self._send_event.set()
        return True

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        ch = self.channels.get(channel_id)
        if ch is None or self._stopped:
            return False
        # net.corrupt over TCP: tamper BEFORE framing/encryption, so the
        # wire stays valid and the remote's decode/signature/merkle checks
        # meet the flipped bits (same semantics as the in-proc site)
        if faults.armed("net.corrupt"):
            msg = faults.mutate("net.corrupt", msg)
        try:
            ch.queue.put_nowait(msg)
        except asyncio.QueueFull:
            return False
        self._send_event.set()
        return True

    def _pick_channel(self) -> Optional[_Channel]:
        """Least recently_sent/priority wins (connection.go:520)."""
        best, best_ratio = None, None
        for ch in self.channels.values():
            if not ch.has_data():
                continue
            ratio = ch.recently_sent / max(1, ch.desc.priority)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    async def _throttle(self, nbytes: int) -> None:
        """Token-bucket send pacing (libs/flowrate analog)."""
        now = time.monotonic()
        self._send_budget = min(
            float(self.config.send_rate),
            self._send_budget + (now - self._budget_at) * self.config.send_rate)
        self._budget_at = now
        self._send_budget -= nbytes
        if self._send_budget < 0:
            await asyncio.sleep(-self._send_budget / self.config.send_rate)

    async def _send_routine(self) -> None:
        try:
            while not self._stopped:
                ch = self._pick_channel()
                if ch is None:
                    self._send_event.clear()
                    # decay counters while idle (connection.go flush)
                    for c in self.channels.values():
                        c.recently_sent = int(c.recently_sent * 0.8)
                    try:
                        await asyncio.wait_for(self._send_event.wait(),
                                               self.config.flush_throttle)
                    except asyncio.TimeoutError:
                        pass
                    continue
                pkt = ch.next_packet()
                if pkt is None:
                    continue
                await self._throttle(len(pkt))
                await self.conn.write(pkt)
                if _p2p_metrics is not None:
                    _p2p_metrics.peer_send_bytes_total.labels(
                        f"{ch.desc.id:#x}").inc(len(pkt))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if not self._stopped:
                await self.on_error(e)

    # -- receiving -----------------------------------------------------------

    async def _recv_throttle(self, nbytes: int) -> None:
        now = time.monotonic()
        self._recv_budget = min(
            float(self.config.recv_rate),
            self._recv_budget + (now - self._recv_budget_at) * self.config.recv_rate)
        self._recv_budget_at = now
        self._recv_budget -= nbytes
        if self._recv_budget < 0:
            await asyncio.sleep(-self._recv_budget / self.config.recv_rate)

    async def _recv_routine(self) -> None:
        try:
            while not self._stopped:
                msg = await self.conn.read_msg()
                await self._recv_throttle(len(msg))
                ln, pos = pw.decode_varint(msg, 0)
                body = msg[pos:pos + ln]
                fields = pw.fields_dict(body)
                if 1 in fields:  # PacketPing
                    self.try_send_raw(_encode_pong())
                elif 2 in fields:  # PacketPong
                    self._pong_pending = False
                elif 3 in fields:  # PacketMsg
                    pkt = pw.fields_dict(fields[3][0])
                    ch_id = pkt.get(1, [0])[0]
                    if _p2p_metrics is not None:
                        _p2p_metrics.peer_receive_bytes_total.labels(
                            f"{ch_id:#x}").inc(len(msg))
                    eof = bool(pkt.get(2, [0])[0])
                    data = pkt.get(3, [b""])[0]
                    ch = self.channels.get(ch_id)
                    if ch is None:
                        raise RuntimeError(f"unknown channel {ch_id:#x}")
                    ch.recving += data
                    if len(ch.recving) > ch.desc.recv_message_capacity:
                        raise RuntimeError(
                            f"recv msg exceeds capacity on {ch_id:#x}")
                    if eof:
                        complete, ch.recving = ch.recving, b""
                        await self.on_receive(ch_id, complete)
                else:
                    raise RuntimeError("unknown packet type")
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError) as e:
            if not self._stopped:
                await self.on_error(e)
        except Exception as e:
            if not self._stopped:
                await self.on_error(e)

    def try_send_raw(self, framed: bytes) -> None:
        t = asyncio.ensure_future(self.conn.write(framed))
        self._raw_sends.add(t)
        t.add_done_callback(self._raw_sends.discard)

    # -- keepalive -----------------------------------------------------------

    async def _ping_routine(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.config.ping_interval)
                self._pong_pending = True
                await self.conn.write(_encode_ping())
                await asyncio.sleep(self.config.pong_timeout)
                if self._pong_pending and not self._stopped:
                    await self.on_error(RuntimeError("pong timeout"))
                    return
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if not self._stopped:
                await self.on_error(e)
