"""SecretConnection: the STS (station-to-station) authenticated-encryption
transport (reference p2p/conn/secret_connection.go:55,92).

Protocol (byte-layout faithful to the reference):

1. exchange ephemeral X25519 pubkeys, each as a length-delimited protobuf
   ``BytesValue`` (secret_connection.go:307);
2. sort the two pubkeys; bind ``EPHEMERAL_LOWER_PUBLIC_KEY``,
   ``EPHEMERAL_UPPER_PUBLIC_KEY`` and the X25519 shared secret into a
   Merlin transcript ``TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH``
   (libs/merlin.py — STROBE-128, matches the upstream merlin test vector);
3. derive two ChaCha20-Poly1305 keys with HKDF-SHA256
   (info ``TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN``; key order
   decided by which ephemeral key sorts lower, secret_connection.go:337);
4. extract the 32-byte challenge ``SECRET_CONNECTION_MAC`` from the
   transcript; each side signs it with its long-lived ed25519 node key and
   sends ``AuthSigMessage{pub_key, sig}`` over the now-encrypted channel;
5. data flows in sealed frames of 1028 bytes (4-byte LE chunk length +
   1024 data) + 16-byte Poly1305 tag, nonce = 4 zero bytes + 8-byte LE
   counter (secret_connection.go:36-41,455).

asyncio StreamReader/StreamWriter based.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes

from ...crypto import Ed25519PubKey, PrivKey, PubKey
from ...libs.merlin import Transcript
from ...libs import protowire as pw

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_SIZE_OVERHEAD = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_SIZE_OVERHEAD

_TRANSCRIPT_LABEL = b"TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH"
_KDF_INFO = b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"


class HandshakeError(Exception):
    pass


def _derive_session(loc_eph_pub: bytes, rem_eph_pub: bytes,
                    dh_secret: bytes) -> Tuple[bytes, bytes, bytes]:
    """Shared handshake key schedule (secret_connection.go:322-351).

    Returns (send_key, recv_key, challenge) from the local perspective.
    """
    if dh_secret == b"\x00" * 32:
        raise HandshakeError("low order point from remote peer")
    lo, hi = sorted([loc_eph_pub, rem_eph_pub])
    transcript = Transcript(_TRANSCRIPT_LABEL)
    transcript.append_message(b"EPHEMERAL_LOWER_PUBLIC_KEY", lo)
    transcript.append_message(b"EPHEMERAL_UPPER_PUBLIC_KEY", hi)
    transcript.append_message(b"DH_SECRET", dh_secret)
    okm = HKDF(algorithm=hashes.SHA256(), length=96, salt=None,
               info=_KDF_INFO).derive(dh_secret)
    if loc_eph_pub == lo:
        recv_key, send_key = okm[0:32], okm[32:64]
    else:
        send_key, recv_key = okm[0:32], okm[32:64]
    challenge = transcript.challenge_bytes(b"SECRET_CONNECTION_MAC", 32)
    return send_key, recv_key, challenge


def _encode_bytes_value(b: bytes) -> bytes:
    w = pw.Writer()
    w.bytes(1, b)
    return pw.length_delimited(w.finish())


async def _read_length_delimited(reader: asyncio.StreamReader,
                                 max_size: int = 1024) -> bytes:
    # uvarint length prefix, then body
    length = 0
    shift = 0
    while True:
        b = await reader.readexactly(1)
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise HandshakeError("varint length overflow")
    if length > max_size:
        raise HandshakeError(f"handshake message too large: {length}")
    return await reader.readexactly(length)


def _encode_auth_sig(pub: PubKey, sig: bytes) -> bytes:
    # AuthSigMessage{ crypto.PublicKey pub_key = 1 (oneof ed25519=1), bytes sig = 2 }
    pk = pw.Writer()
    pk.bytes(1, pub.bytes())  # PublicKey.ed25519
    w = pw.Writer()
    w.message(1, pk.finish())
    w.bytes(2, sig)
    return pw.length_delimited(w.finish())


def _decode_auth_sig(body: bytes) -> Tuple[PubKey, bytes]:
    fields = pw.fields_dict(body)
    if 1 not in fields or 2 not in fields:
        raise HandshakeError("malformed AuthSigMessage")
    pk_fields = pw.fields_dict(fields[1][0])
    if 1 not in pk_fields:
        raise HandshakeError("unsupported pubkey type in AuthSigMessage")
    return Ed25519PubKey(pk_fields[1][0]), fields[2][0]


class _Nonce:
    __slots__ = ("counter",)

    def __init__(self):
        self.counter = 0

    def bytes(self) -> bytes:
        return b"\x00\x00\x00\x00" + self.counter.to_bytes(8, "little")

    def incr(self) -> None:
        self.counter += 1
        if self.counter >= 1 << 64:
            raise RuntimeError("nonce overflow; terminate session")


# -- sans-I/O frame helpers shared by the async and blocking wrappers --------

def _seal_frames(aead, nonce: _Nonce, data: bytes) -> bytes:
    """Chunk ``data`` into sealed 1044-byte frames
    (secret_connection.go:187 Write)."""
    out = bytearray()
    while data:
        chunk, data = data[:DATA_MAX_SIZE], data[DATA_MAX_SIZE:]
        frame = bytearray(TOTAL_FRAME_SIZE)
        frame[0:4] = len(chunk).to_bytes(4, "little")
        frame[4:4 + len(chunk)] = chunk
        out += aead.encrypt(nonce.bytes(), bytes(frame), None)
        nonce.incr()
    return bytes(out)


def _open_frame(aead, nonce: _Nonce, sealed: bytes) -> bytes:
    """One sealed frame -> its data chunk (secret_connection.go:143 Read)."""
    frame = aead.decrypt(nonce.bytes(), sealed, None)
    nonce.incr()
    chunk_len = int.from_bytes(frame[0:4], "little")
    if chunk_len > DATA_MAX_SIZE:
        raise RuntimeError("chunk length exceeds dataMaxSize")
    return frame[4:4 + chunk_len]


class SecretConnection:
    """Encrypted, authenticated stream over (reader, writer)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 send_key: bytes, recv_key: bytes, remote_pubkey: PubKey):
        self._reader = reader
        self._writer = writer
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = _Nonce()
        self._recv_nonce = _Nonce()
        self._recv_buffer = b""
        self.remote_pubkey = remote_pubkey

    # -- handshake -----------------------------------------------------------

    @classmethod
    async def make(cls, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                   local_priv: PrivKey) -> "SecretConnection":
        """(secret_connection.go:92 MakeSecretConnection)"""
        eph_priv = X25519PrivateKey.generate()
        loc_eph_pub = eph_priv.public_key().public_bytes_raw()

        writer.write(_encode_bytes_value(loc_eph_pub))
        await writer.drain()
        rem_msg = await _read_length_delimited(reader)
        rem_fields = pw.fields_dict(rem_msg)
        rem_eph_pub = rem_fields.get(1, [b""])[0]
        if len(rem_eph_pub) != 32:
            raise HandshakeError("bad ephemeral pubkey length")

        dh_secret = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph_pub))
        send_key, recv_key, challenge = _derive_session(
            loc_eph_pub, rem_eph_pub, dh_secret)

        sc = cls(reader, writer, send_key, recv_key, remote_pubkey=None)

        sig = local_priv.sign(challenge)
        await sc.write_msg(_encode_auth_sig(local_priv.pub_key(), sig))
        auth_body = await sc.read_msg(max_size=1024)
        # strip the inner varint length prefix
        ln, pos = pw.decode_varint(auth_body, 0)
        rem_pub, rem_sig = _decode_auth_sig(auth_body[pos:pos + ln])
        if not rem_pub.verify_signature(challenge, rem_sig):
            raise HandshakeError("challenge verification failed")
        sc.remote_pubkey = rem_pub
        return sc

    # -- framing -------------------------------------------------------------

    async def write(self, data: bytes) -> None:
        """Chunk into sealed frames (secret_connection.go:187 Write)."""
        self._writer.write(_seal_frames(self._send_aead, self._send_nonce, data))
        await self._writer.drain()

    async def read(self) -> bytes:
        """One chunk (<= 1024 bytes) from the next frame, or buffered rest."""
        if self._recv_buffer:
            out, self._recv_buffer = self._recv_buffer, b""
            return out
        sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        return _open_frame(self._recv_aead, self._recv_nonce, sealed)

    async def read_exactly(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = await self.read()
            if not chunk:
                raise asyncio.IncompleteReadError(out, n)
            take = min(n - len(out), len(chunk))
            out += chunk[:take]
            self._recv_buffer = chunk[take:] + self._recv_buffer
        return out

    # -- length-delimited messages over the encrypted stream -----------------

    async def write_msg(self, framed: bytes) -> None:
        await self.write(framed)

    async def read_msg(self, max_size: int = 10 * 1024 * 1024) -> bytes:
        """Read a uvarint-length-delimited message; returns prefix+body."""
        header = b""
        while True:
            b = await self.read_exactly(1)
            header += b
            if not b[0] & 0x80:
                break
            if len(header) > 5:
                raise RuntimeError("varint overflow")
        length, _ = pw.decode_varint(header, 0)
        if length > max_size:
            raise RuntimeError(f"message too large: {length}")
        body = await self.read_exactly(length)
        return header + body

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


def _sock_recv_exact(sock, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("secret connection closed")
        out += chunk
    return out


def _sock_read_length_delimited(sock, max_size: int = 1024) -> bytes:
    """Blocking twin of _read_length_delimited (handshake plaintext phase)."""
    length = 0
    shift = 0
    while True:
        b = _sock_recv_exact(sock, 1)
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise HandshakeError("varint length overflow")
    if length > max_size:
        raise HandshakeError(f"handshake message too large: {length}")
    return _sock_recv_exact(sock, length)


class SyncSecretConnection:
    """The same STS protocol over a blocking socket, for threaded endpoints
    (the remote-signer privval connection — reference wraps tcp:// privval
    links in SecretConnection, privval/socket_listeners.go:66).

    Wire-compatible with :class:`SecretConnection`; one may sit on either
    end of the other.
    """

    def __init__(self, sock, send_key: bytes, recv_key: bytes,
                 remote_pubkey: Optional[PubKey]):
        self._sock = sock
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = _Nonce()
        self._recv_nonce = _Nonce()
        self._recv_buffer = b""
        self.remote_pubkey = remote_pubkey

    def _recv_exact(self, n: int) -> bytes:
        return _sock_recv_exact(self._sock, n)

    @classmethod
    def make(cls, sock, local_priv: PrivKey,
             expected_remote_key: Optional[bytes] = None) -> "SyncSecretConnection":
        eph_priv = X25519PrivateKey.generate()
        loc_eph_pub = eph_priv.public_key().public_bytes_raw()

        sock.sendall(_encode_bytes_value(loc_eph_pub))
        rem_msg = _sock_read_length_delimited(sock)
        rem_fields = pw.fields_dict(rem_msg)
        rem_eph_pub = rem_fields.get(1, [b""])[0]
        if len(rem_eph_pub) != 32:
            raise HandshakeError("bad ephemeral pubkey length")

        dh_secret = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph_pub))
        send_key, recv_key, challenge = _derive_session(
            loc_eph_pub, rem_eph_pub, dh_secret)

        sc = cls(sock, send_key, recv_key, remote_pubkey=None)
        sig = local_priv.sign(challenge)
        sc.write(_encode_auth_sig(local_priv.pub_key(), sig))
        auth_body = sc.read_msg(max_size=1024)
        ln, pos = pw.decode_varint(auth_body, 0)
        rem_pub, rem_sig = _decode_auth_sig(auth_body[pos:pos + ln])
        if not rem_pub.verify_signature(challenge, rem_sig):
            raise HandshakeError("challenge verification failed")
        if (expected_remote_key is not None
                and rem_pub.bytes() != expected_remote_key):
            raise HandshakeError("remote static key does not match expected key")
        sc.remote_pubkey = rem_pub
        return sc

    def write(self, data: bytes) -> None:
        self._sock.sendall(_seal_frames(self._send_aead, self._send_nonce, data))

    def read(self) -> bytes:
        if self._recv_buffer:
            out, self._recv_buffer = self._recv_buffer, b""
            return out
        sealed = self._recv_exact(SEALED_FRAME_SIZE)
        return _open_frame(self._recv_aead, self._recv_nonce, sealed)

    def read_exactly(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.read()
            if not chunk:
                raise ConnectionError("secret connection closed")
            take = min(n - len(out), len(chunk))
            out += chunk[:take]
            self._recv_buffer = chunk[take:] + self._recv_buffer
        return out

    def read_msg(self, max_size: int = 1024 * 1024) -> bytes:
        """uvarint-length-delimited message; returns prefix+body."""
        header = b""
        while True:
            b = self.read_exactly(1)
            header += b
            if not b[0] & 0x80:
                break
            if len(header) > 5:
                raise RuntimeError("varint overflow")
        length, _ = pw.decode_varint(header, 0)
        if length > max_size:
            raise RuntimeError(f"message too large: {length}")
        body = self.read_exactly(length)
        return header + body

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
