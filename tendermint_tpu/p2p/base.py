"""Reactor/Peer interfaces (reference p2p/base_reactor.go:15, p2p/peer.go:23)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ChannelDescriptor:
    """(p2p/conn/connection.go:746 ChannelDescriptor)"""

    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = 1048576


@dataclass
class Envelope:
    channel_id: int
    message: bytes
    sender: str = ""


class Peer:
    """A connected peer (p2p/peer.go:23). Implementations: inproc, tcp."""

    def __init__(self, peer_id: str, outbound: bool = False,
                 persistent: bool = False):
        self.id = peer_id
        self.outbound = outbound
        self.persistent = persistent
        # reactors hang per-peer state here (reference peer.Set/Get)
        self.data: Dict[str, Any] = {}

    def send(self, channel_id: int, msg: bytes) -> bool:
        """Queue msg; blocks-by-dropping if the channel is saturated (TrySend
        semantics — asyncio reactors use the async send path below)."""
        raise NotImplementedError

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        raise NotImplementedError

    def is_running(self) -> bool:
        raise NotImplementedError

    async def stop(self) -> None:
        raise NotImplementedError

    def set(self, key: str, value: Any) -> None:
        self.data[key] = value

    def get(self, key: str) -> Any:
        return self.data.get(key)

    def __repr__(self):
        return f"Peer({self.id[:12]})"


class Reactor:
    """(p2p/base_reactor.go:15)"""

    def __init__(self, name: str):
        self.name = name
        self.switch = None  # set by Switch.add_reactor

    def get_channels(self) -> List[ChannelDescriptor]:
        return []

    def set_switch(self, switch) -> None:
        self.switch = switch

    async def start(self) -> None:
        pass

    async def stop(self) -> None:
        pass

    def init_peer(self, peer: Peer) -> Peer:
        return peer

    async def add_peer(self, peer: Peer) -> None:
        pass

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        pass

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        pass
