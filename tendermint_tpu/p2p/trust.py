"""Peer trust metrics (reference p2p/trust/metric.go:86, store.go).

Each peer accumulates good/bad events; at interval boundaries the interval's
proportion folds into a faded history. The trust value combines:

* proportional component — this interval's good/(good+bad);
* integral component — the history EWMA;
* a derivative penalty when the trend is downward (the reference weights
  negative derivatives so a recently-flapping peer scores below a stale
  one, metric.go:258 calcTrustValue).

Values live in [0, 1]. The store persists scores across restarts and the
switch consults :meth:`TrustMetricStore.banned` before (re)dialing — a peer
whose score sinks below the ban threshold is quarantined for
``ban_duration`` seconds rather than forever (reference store keys peers by
ID in a db-backed store, store.go:38).

Design deltas from the reference, on purpose: time is injected (monotonic
callable) so tests drive interval rollover deterministically, and the
persistence format is a single JSON document per store rather than one
leveldb row per peer — the peer counts here (dozens) don't justify a table.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional

# reference defaults (metric.go:17-24): proportional .4, integral .6,
# 1-minute intervals over a (shortened) tracking window
PROPORTIONAL_WEIGHT = 0.4
INTEGRAL_WEIGHT = 0.6
DEFAULT_INTERVAL = 60.0
HISTORY_ALPHA = 0.2          # EWMA fade per interval
DEFAULT_BAN_THRESHOLD = 0.25
DEFAULT_BAN_DURATION = 600.0
# never quarantine on fewer cumulative bad events than this: a single
# transient flap (one dropped connection scored while the metric has no
# good history yet) can sink a fresh peer's value below the threshold,
# and a 10-minute ban of an honest validator costs more than tolerating
# a few bad messages from a dishonest one
DEFAULT_MIN_BAN_EVENTS = 4.0


class TrustMetric:
    def __init__(self, interval: float = DEFAULT_INTERVAL,
                 now: Callable[[], float] = time.monotonic):
        self._now = now
        self.interval = interval
        self.good = 0.0
        self.bad = 0.0
        self.history: Optional[float] = None  # EWMA of interval proportions
        self.last_value = 1.0                 # previous interval's value
        self._interval_start = now()

    # -- events ------------------------------------------------------------

    def record_good(self, n: float = 1.0) -> None:
        self._maybe_roll()
        self.good += n

    def record_bad(self, n: float = 1.0) -> None:
        self._maybe_roll()
        self.bad += n

    # -- value -------------------------------------------------------------

    def value(self) -> float:
        """Current trust in [0, 1] (metric.go:258 calcTrustValue)."""
        self._maybe_roll()
        hist = self.history
        if self.good + self.bad == 0:
            # no evidence THIS interval: score on history alone (a peer that
            # went quiet right after flapping must not snap back to 1.0)
            r = hist if hist is not None else 1.0
        else:
            r = self._proportion()
        if hist is None:
            hist = r
        v = PROPORTIONAL_WEIGHT * r + INTEGRAL_WEIGHT * hist
        d = v - self.last_value
        if d < 0:
            # negative trend weighted in, like the reference's derivative
            # term: a peer getting worse scores below its averages
            v += 0.5 * d
        return max(0.0, min(1.0, v))

    def _proportion(self) -> float:
        total = self.good + self.bad
        if total == 0:
            return 1.0  # no evidence: neutral-good, like a fresh peer
        return self.good / total

    def _maybe_roll(self) -> None:
        now = self._now()
        while now - self._interval_start >= self.interval:
            if self.good + self.bad > 0:  # empty intervals don't fade history
                r = self._proportion()
                self.history = (r if self.history is None
                                else HISTORY_ALPHA * r
                                + (1 - HISTORY_ALPHA) * self.history)
                self.last_value = (PROPORTIONAL_WEIGHT * r
                                   + INTEGRAL_WEIGHT * self.history)
                self.good = self.bad = 0.0
            self._interval_start += self.interval
            if now - self._interval_start > 100 * self.interval:
                # long-idle peer: skip ahead instead of looping for hours
                self._interval_start = now
                break

    # -- persistence -------------------------------------------------------

    def to_doc(self) -> dict:
        self._maybe_roll()
        return {"history": self.history, "last_value": self.last_value}

    @classmethod
    def from_doc(cls, doc: dict, interval: float = DEFAULT_INTERVAL,
                 now: Callable[[], float] = time.monotonic) -> "TrustMetric":
        m = cls(interval=interval, now=now)
        m.history = doc.get("history")
        m.last_value = float(doc.get("last_value", 1.0))
        return m


class TrustMetricStore:
    """Per-peer metrics + ban decisions, persisted as one JSON doc
    (reference p2p/trust/store.go:38 TrustMetricStore)."""

    def __init__(self, db=None, key: bytes = b"p2p:trust",
                 interval: float = DEFAULT_INTERVAL,
                 ban_threshold: float = DEFAULT_BAN_THRESHOLD,
                 ban_duration: float = DEFAULT_BAN_DURATION,
                 min_ban_events: float = DEFAULT_MIN_BAN_EVENTS,
                 now: Callable[[], float] = time.monotonic):
        self._db = db
        self._key = key
        self._now = now
        self._interval = interval
        self.ban_threshold = ban_threshold
        self.ban_duration = ban_duration
        self.min_ban_events = min_ban_events
        self.metrics: Dict[str, TrustMetric] = {}
        self._bans: Dict[str, float] = {}  # peer id -> ban expiry (now() base)
        self._bad_events: Dict[str, float] = {}  # cumulative, reset on parole
        self._load()

    def get(self, peer_id: str) -> TrustMetric:
        m = self.metrics.get(peer_id)
        if m is None:
            m = TrustMetric(interval=self._interval, now=self._now)
            self.metrics[peer_id] = m
        return m

    # -- switch-facing API --------------------------------------------------

    def peer_good(self, peer_id: str, n: float = 1.0) -> None:
        self.get(peer_id).record_good(n)

    def peer_bad(self, peer_id: str, n: float = 1.0) -> None:
        m = self.get(peer_id)
        m.record_bad(n)
        total_bad = self._bad_events.get(peer_id, 0.0) + n
        self._bad_events[peer_id] = total_bad
        if (total_bad >= self.min_ban_events
                and m.value() < self.ban_threshold):
            self._bans[peer_id] = self._now() + self.ban_duration

    def value(self, peer_id: str) -> float:
        return self.get(peer_id).value()

    def banned(self, peer_id: str) -> bool:
        expiry = self._bans.get(peer_id)
        if expiry is None:
            return False
        if self._now() >= expiry:
            del self._bans[peer_id]
            # parole: reset the metric so the peer isn't instantly re-banned
            # by its own history (reference store re-creates on re-add)
            self.metrics.pop(peer_id, None)
            self._bad_events.pop(peer_id, None)
            return False
        return True

    # -- persistence ---------------------------------------------------------

    def save(self) -> None:
        if self._db is None:
            return
        doc = {
            "peers": {pid: m.to_doc() for pid, m in self.metrics.items()},
            "bans": {pid: max(0.0, exp - self._now())
                     for pid, exp in self._bans.items()},
            # persisted so a misbehaving peer can't reset its event count
            # (and with it the ban floor) by bouncing the node
            "bad_events": dict(self._bad_events),
        }
        self._db.set(self._key, json.dumps(doc).encode())

    def _load(self) -> None:
        if self._db is None:
            return
        raw = self._db.get(self._key)
        if not raw:
            return
        try:
            doc = json.loads(raw.decode())
        except ValueError:
            return
        for pid, mdoc in doc.get("peers", {}).items():
            self.metrics[pid] = TrustMetric.from_doc(
                mdoc, interval=self._interval, now=self._now)
        now = self._now()
        for pid, remaining in doc.get("bans", {}).items():
            if remaining > 0:
                self._bans[pid] = now + float(remaining)
        for pid, count in doc.get("bad_events", {}).items():
            self._bad_events[pid] = float(count)
