"""State-sync reactor — channels Snapshot=0x60, Chunk=0x61
(reference statesync/reactor.go:22,31): serves local app snapshots to
syncing peers and feeds inbound snapshots/chunks to the Syncer.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from ..abci import types as abci
from ..p2p import CHUNK_CHANNEL, SNAPSHOT_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from .msgs import (
    ChunkRequest,
    ChunkResponse,
    SnapshotsRequest,
    SnapshotsResponse,
    decode_msg,
    encode_msg,
)
from .syncer import Syncer

logger = logging.getLogger("tmtpu.statesync")

# advertise at most this many snapshots per request (reactor.go)
RECENT_SNAPSHOTS = 10


class StateSyncReactor(Reactor):
    def __init__(self, proxy_snapshot, proxy_query):
        super().__init__("STATESYNC")
        self.app_snapshot = proxy_snapshot
        self.app_query = proxy_query
        self.syncer: Optional[Syncer] = None

    def get_channels(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10,
                              recv_message_capacity=4 << 20),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=4,
                              recv_message_capacity=16 << 20),
        ]

    async def add_peer(self, peer: Peer) -> None:
        # ask new peers for their snapshots while we are syncing
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, encode_msg(SnapshotsRequest()))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        if self.syncer is not None:
            self.syncer.pool.remove_peer(peer.id)

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = decode_msg(msg_bytes)
        if isinstance(msg, SnapshotsRequest):
            for s in self._local_snapshots():
                peer.try_send(SNAPSHOT_CHANNEL, encode_msg(
                    SnapshotsResponse(s.height, s.format, s.chunks, s.hash,
                                      s.metadata)))
        elif isinstance(msg, SnapshotsResponse):
            if self.syncer is not None:
                if self.syncer.add_snapshot(peer.id, msg):
                    logger.info("discovered snapshot h=%d fmt=%d from %s",
                                msg.height, msg.format, peer.id[:8])
        elif isinstance(msg, ChunkRequest):
            resp = self.app_snapshot.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(msg.height, msg.format, msg.index))
            missing = not resp.chunk
            peer.try_send(CHUNK_CHANNEL, encode_msg(ChunkResponse(
                msg.height, msg.format, msg.index, resp.chunk, missing)))
        elif isinstance(msg, ChunkResponse):
            if self.syncer is not None:
                self.syncer.add_chunk(msg, peer.id)

    def _local_snapshots(self):
        try:
            resp = self.app_snapshot.list_snapshots(abci.RequestListSnapshots())
        except Exception:
            return []
        snaps = sorted(resp.snapshots, key=lambda s: (s.height, s.format),
                       reverse=True)
        return snaps[:RECENT_SNAPSHOTS]

    # -- sync orchestration (reactor.go Sync / node.go:648 startStateSync) ---

    async def sync(self, state_provider, discovery_time: float = 5.0):
        """Run a snapshot restore; -> (state, commit). The caller bootstraps
        the stores and hands off to fast sync / consensus."""
        async def request_chunk(peer_id, height, fmt, idx):
            peer = self.switch.peers.get(peer_id) if self.switch else None
            if peer is None:
                raise RuntimeError(f"peer {peer_id[:8]} gone")
            peer.try_send(CHUNK_CHANNEL, encode_msg(
                ChunkRequest(height, fmt, idx)))

        self.syncer = Syncer(self.app_snapshot, self.app_query, state_provider,
                             request_chunk)
        if self.switch is not None:
            self.switch.broadcast(SNAPSHOT_CHANNEL, encode_msg(SnapshotsRequest()))
        try:
            return await self.syncer.sync_any(discovery_time)
        finally:
            self.syncer = None
