"""State-sync reactor — channels Snapshot=0x60, Chunk=0x61
(reference statesync/reactor.go:22,31): serves local app snapshots to
syncing peers and feeds inbound snapshots/chunks to the Syncer.

The SERVING side carries two adversarial fault sites
(``statesync.lying_snapshot`` / ``statesync.lying_chunk``, libs/faults.py):
when armed, this node becomes the Byzantine peer — advertising snapshots
with tampered hashes or returning corrupted chunk bytes — so a chaos run's
VICTIMS exercise their real verification + peer-banning paths against it.
"""

from __future__ import annotations

import asyncio
import logging
import random
import zlib
from typing import List, Optional

from ..abci import types as abci
from ..libs.faults import faults
from ..libs.metrics import Registry, StateSyncMetrics
from ..libs.peerscore import PeerScoreboard
from ..p2p import CHUNK_CHANNEL, SNAPSHOT_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from .msgs import (
    ChunkRequest,
    ChunkResponse,
    SnapshotsRequest,
    SnapshotsResponse,
    decode_msg,
    encode_msg,
)
from .syncer import CHUNK_FETCHERS, CHUNK_REQUEST_TIMEOUT, DISCOVERY_ROUNDS, Syncer

logger = logging.getLogger("tmtpu.statesync")

# advertise at most this many snapshots per request (reactor.go)
RECENT_SNAPSHOTS = 10


class StateSyncReactor(Reactor):
    def __init__(self, proxy_snapshot, proxy_query):
        super().__init__("STATESYNC")
        self.app_snapshot = proxy_snapshot
        self.app_query = proxy_query
        self.syncer: Optional[Syncer] = None
        # node.py rebinds this onto the shared registry; standalone
        # reactors (tests) keep a private set
        self.metrics = StateSyncMetrics(Registry())
        # survives the syncer teardown so debugdump can explain a restore
        # that already failed/finished
        self.last_progress: Optional[dict] = None

    def set_metrics(self, m) -> None:
        self.metrics = m

    def get_channels(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10,
                              recv_message_capacity=4 << 20),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=4,
                              recv_message_capacity=16 << 20),
        ]

    async def add_peer(self, peer: Peer) -> None:
        # ask new peers for their snapshots while we are syncing
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, encode_msg(SnapshotsRequest()))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        if self.syncer is not None:
            self.syncer.pool.remove_peer(peer.id)

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = decode_msg(msg_bytes)
        if isinstance(msg, SnapshotsRequest):
            for s in self._local_snapshots():
                # statesync.lying_snapshot: advertise a bogus hash — the
                # victim restores the real chunks, fails its trusted-app-
                # hash check, and must blame/ban the advertiser
                hash_ = faults.mutate("statesync.lying_snapshot", s.hash)
                peer.try_send(SNAPSHOT_CHANNEL, encode_msg(
                    SnapshotsResponse(s.height, s.format, s.chunks, hash_,
                                      s.metadata)))
        elif isinstance(msg, SnapshotsResponse):
            if self.syncer is not None:
                if self.syncer.add_snapshot(peer.id, msg):
                    logger.info("discovered snapshot h=%d fmt=%d from %s",
                                msg.height, msg.format, peer.id[:8])
        elif isinstance(msg, ChunkRequest):
            resp = self.app_snapshot.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(msg.height, msg.format, msg.index))
            missing = not resp.chunk
            # statesync.lying_chunk: serve corrupted chunk bytes — the
            # victim's app detects the tamper (per-chunk hash or whole-blob
            # check) and its syncer strikes/bans this sender
            chunk = faults.mutate("statesync.lying_chunk", resp.chunk)
            peer.try_send(CHUNK_CHANNEL, encode_msg(ChunkResponse(
                msg.height, msg.format, msg.index, chunk, missing)))
        elif isinstance(msg, ChunkResponse):
            if self.syncer is not None:
                self.syncer.add_chunk(msg, peer.id)

    def _local_snapshots(self):
        try:
            resp = self.app_snapshot.list_snapshots(abci.RequestListSnapshots())
        except Exception:
            return []
        snaps = sorted(resp.snapshots, key=lambda s: (s.height, s.format),
                       reverse=True)
        return snaps[:RECENT_SNAPSHOTS]

    # -- sync orchestration (reactor.go Sync / node.go:648 startStateSync) ---

    def make_scoreboard(self, ban_threshold: int = 3,
                        seed: Optional[int] = None) -> PeerScoreboard:
        """A scoreboard wired to this reactor's metric set. node.py builds
        it up front so the light-client state provider (witness
        cross-checks) and the syncer (chunk blame) share one ledger."""
        if seed is None:
            seed = faults.seed
        return PeerScoreboard(
            ban_threshold=ban_threshold, seed=seed, name="statesync",
            bans_counter=self.metrics.peer_bans_total,
            retries_counter=self.metrics.sync_retries_total)

    async def sync(self, state_provider, discovery_time: float = 5.0,
                   chunk_fetchers: int = CHUNK_FETCHERS,
                   chunk_timeout: float = CHUNK_REQUEST_TIMEOUT,
                   discovery_rounds: int = DISCOVERY_ROUNDS,
                   ban_threshold: int = 3,
                   seed: Optional[int] = None,
                   scoreboard: Optional[PeerScoreboard] = None):
        """Run a snapshot restore; -> (state, commit). The caller bootstraps
        the stores and hands off to fast sync / consensus. All randomness
        (peer rotation, backoff jitter) derives from `seed` (default: the
        fault-plane seed) so chaos runs replay."""
        async def request_chunk(peer_id, height, fmt, idx):
            peer = self.switch.peers.get(peer_id) if self.switch else None
            if peer is None:
                raise RuntimeError(f"peer {peer_id[:8]} gone")
            peer.try_send(CHUNK_CHANNEL, encode_msg(
                ChunkRequest(height, fmt, idx)))

        def rediscover():
            if self.switch is not None:
                self.switch.broadcast(SNAPSHOT_CHANNEL,
                                      encode_msg(SnapshotsRequest()))

        if seed is None:
            seed = faults.seed
        m = self.metrics
        if scoreboard is None:
            scoreboard = self.make_scoreboard(ban_threshold, seed)
        self.syncer = Syncer(
            self.app_snapshot, self.app_query, state_provider, request_chunk,
            chunk_fetchers=chunk_fetchers, chunk_timeout=chunk_timeout,
            rng=random.Random(zlib.crc32(f"{seed}|statesync.fetch".encode())),
            scoreboard=scoreboard, metrics=m)
        rediscover()
        try:
            return await self.syncer.sync_any(
                discovery_time, rediscover=rediscover,
                discovery_rounds=discovery_rounds)
        finally:
            self.last_progress = self.syncer.progress()
            self.syncer = None
