"""State sync (reference statesync/): bootstrap a fresh node from an
application snapshot served by peers, verified against a light-client-
obtained header, then hand off to fast sync / consensus."""

from .reactor import StateSyncReactor  # noqa: F401
from .syncer import Syncer, SyncError  # noqa: F401
from .stateprovider import LightClientStateProvider, StateProvider  # noqa: F401
