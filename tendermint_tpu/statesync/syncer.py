"""Snapshot restore orchestration (reference statesync/syncer.go:145
SyncAny): pick a snapshot advertised by peers, OfferSnapshot to the app,
fetch chunks with parallel fetchers, ApplySnapshotChunk with
retry/refetch/reject semantics, and verify the restored app hash against a
light-client-obtained header.

Hardened for UNTRUSTED peers (the adversarial setting of arXiv 2410.03347:
a bootstrapping node must survive Byzantine data providers, not just
silent ones):

* every chunk fetch routes through a :class:`PeerScoreboard` — bad chunks
  (app reject/refetch, timeouts) put the sender in exponential backoff and
  ban it after K strikes; snapshot-level verification failures blame every
  advertiser of that snapshot;
* peer selection is DETERMINISTIC: the sorted advertiser list is shuffled
  once per peer-set by the reactor-injected seeded RNG, then rotated per
  retry — a chaos run replays its fetch schedule exactly, and repeated
  retries of one chunk walk every advertiser instead of re-rolling dice;
* snapshot discovery is a LOOP, not a single fixed sleep: an empty pool
  re-asks the net (``rediscover`` callback) up to ``discovery_rounds``
  times before giving up with ErrNoSnapshots — the caller (node.py) then
  falls back to fast sync from genesis instead of dying.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..libs.peerscore import PeerScoreboard
from .chunks import ChunkQueue
from .stateprovider import StateProvider

logger = logging.getLogger("tmtpu.statesync")

# defaults; config.statesync.chunk_fetchers / chunk_request_timeout (with
# TMTPU_STATESYNC_CHUNK_FETCHERS / TMTPU_STATESYNC_CHUNK_TIMEOUT env
# overrides) are the operator-facing knobs — node.py passes them through
CHUNK_FETCHERS = 4
CHUNK_REQUEST_TIMEOUT = 10.0
DISCOVERY_ROUNDS = 4


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    pass


class ErrSnapshotRejected(SyncError):
    """``blame_advertisers=True`` marks CONTENT failures — the restored
    data contradicted the advertised hash or the trusted app hash — where
    every advertiser of the key provably vouched for bad data. App-policy
    rejections (offer refused, unsupported format) and exhausted-peer
    aborts carry no such proof and must not ban anyone."""

    def __init__(self, msg: str, blame_advertisers: bool = False,
                 retriable: bool = False):
        super().__init__(msg)
        self.blame_advertisers = blame_advertisers
        # retriable: the snapshot CONTENT was never disproven (e.g. every
        # advertiser vanished/was banned mid-restore) — drop it from the
        # current pool but let a later honest advertisement re-add it
        self.retriable = retriable


class ErrRetrySnapshot(SyncError):
    pass


class ErrAbort(SyncError):
    pass


@dataclass(frozen=True)
class SnapshotKey:
    height: int
    format: int
    chunks: int
    hash: bytes


@dataclass
class SnapshotPool:
    """Snapshots advertised by peers, best (highest, then format) first."""

    snapshots: Dict[SnapshotKey, Set[str]] = field(default_factory=dict)
    rejected: Set[SnapshotKey] = field(default_factory=set)
    metadata: Dict[SnapshotKey, bytes] = field(default_factory=dict)

    def add(self, peer_id: str, height: int, fmt: int, chunks: int,
            hash_: bytes, meta: bytes) -> bool:
        key = SnapshotKey(height, fmt, chunks, hash_)
        if key in self.rejected:
            return False
        new = key not in self.snapshots
        self.snapshots.setdefault(key, set()).add(peer_id)
        self.metadata[key] = meta
        return new

    def best(self) -> Optional[SnapshotKey]:
        cands = [k for k in self.snapshots if k not in self.rejected]
        if not cands:
            return None
        # hash is the deterministic tie-break: two same-height snapshots
        # (one honest, one a lie) are tried in a stable order across runs
        return max(cands, key=lambda k: (k.height, k.format, k.hash))

    def reject(self, key: SnapshotKey) -> None:
        self.rejected.add(key)
        self.snapshots.pop(key, None)

    def forget(self, key: SnapshotKey) -> None:
        """Drop a key WITHOUT blacklisting it — a fresh advertisement (a
        new honest peer) may legitimately re-add it."""
        self.snapshots.pop(key, None)
        self.metadata.pop(key, None)

    def reject_format(self, fmt: int) -> None:
        for k in list(self.snapshots):
            if k.format == fmt:
                self.reject(k)

    def remove_peer(self, peer_id: str) -> None:
        for k, peers in list(self.snapshots.items()):
            peers.discard(peer_id)
            if not peers:
                del self.snapshots[k]

    def peers_of(self, key: SnapshotKey) -> List[str]:
        # sorted: set iteration order depends on PYTHONHASHSEED — a
        # replayable fetch schedule needs a stable peer order
        return sorted(self.snapshots.get(key, ()))


class Syncer:
    """(syncer.go) Drives one snapshot restore against the app."""

    def __init__(self, proxy_snapshot, proxy_query, state_provider: StateProvider,
                 request_chunk, chunk_fetchers: int = CHUNK_FETCHERS,
                 chunk_timeout: float = CHUNK_REQUEST_TIMEOUT,
                 rng: Optional[random.Random] = None,
                 scoreboard: Optional[PeerScoreboard] = None,
                 metrics=None):
        self.app_snapshot = proxy_snapshot
        self.app_query = proxy_query
        self.state_provider = state_provider
        self.request_chunk = request_chunk  # async (peer_id, height, fmt, idx)
        self.pool = SnapshotPool()
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        # injected by the reactor (seeded from the fault-plane seed) so
        # fault runs replay; standalone harnesses get a fixed default
        self.rng = rng if rng is not None else random.Random(0)
        self.scoreboard = scoreboard if scoreboard is not None \
            else PeerScoreboard(name="statesync")
        self.metrics = metrics              # libs.metrics.StateSyncMetrics
        self.chunks: Optional[ChunkQueue] = None
        self._current: Optional[SnapshotKey] = None
        self._applied = 0
        self._discovery_round = 0
        # per-peer-set deterministic rotation order + per-chunk attempts
        self._order_cache: Tuple[Tuple[str, ...], List[str]] = ((), [])
        self._attempts: Dict[int, int] = {}

    # -- inbound (reactor feeds these) ---------------------------------------

    def add_snapshot(self, peer_id: str, resp) -> bool:
        new = self.pool.add(peer_id, resp.height, resp.format, resp.chunks,
                            resp.hash, resp.metadata)
        if new and self.metrics is not None:
            self.metrics.snapshots_offered_total.inc()
        return new

    def add_chunk(self, resp, sender: str) -> None:
        cur = self._current
        if (self.chunks is None or cur is None
                or resp.height != cur.height or resp.format != cur.format):
            return  # late or mismatched response from a previous attempt
        if resp.missing:
            self.chunks.discard(resp.index)
            return
        if self.chunks.add(resp.index, resp.chunk, sender) \
                and self.metrics is not None:
            self.metrics.chunks_fetched_total.inc()

    # -- progress (debugdump / watchdog post-mortems) ------------------------

    def progress(self) -> dict:
        """JSON-safe snapshot of where the restore stands — a wedged
        bootstrap must be diagnosable from the bundle alone."""
        cur = self._current
        return {
            "snapshot": None if cur is None else {
                "height": cur.height, "format": cur.format,
                "chunks": cur.chunks, "hash": cur.hash.hex(),
            },
            "chunks_applied": self._applied,
            "chunks_total": 0 if cur is None else cur.chunks,
            "discovery_round": self._discovery_round,
            "pool_snapshots": len(self.pool.snapshots),
            "pool_rejected": len(self.pool.rejected),
            "peer_scores": self.scoreboard.snapshot(),
        }

    # -- orchestration -------------------------------------------------------

    async def sync_any(self, discovery_time: float = 5.0,
                       rediscover: Optional[Callable[[], None]] = None,
                       discovery_rounds: int = DISCOVERY_ROUNDS):
        """(syncer.go:145 SyncAny) -> (state, commit) for the restored height.
        Tries snapshots best-first; an empty pool re-asks the net up to
        `discovery_rounds` times before raising ErrNoSnapshots."""
        rounds_left = max(1, discovery_rounds)
        await asyncio.sleep(discovery_time)
        while True:
            key = self.pool.best()
            if key is None:
                rounds_left -= 1
                if rounds_left <= 0:
                    raise ErrNoSnapshots("no viable snapshots remain")
                self._discovery_round += 1
                if self.metrics is not None:
                    self.metrics.discovery_rounds_total.inc()
                logger.info("snapshot pool empty; re-discovering "
                            "(%d rounds left)", rounds_left)
                if rediscover is not None:
                    rediscover()
                await asyncio.sleep(discovery_time)
                continue
            advertisers = self.pool.peers_of(key)
            try:
                return await self._sync(key)
            except ErrSnapshotRejected as e:
                logger.info("snapshot %d/%d rejected (%s); trying next",
                            key.height, key.format, e)
                if e.blame_advertisers:
                    # content-level rejection: every peer that advertised
                    # this snapshot vouched for bad data (per-chunk lies
                    # were already attributed to their senders upstream)
                    self._blame(advertisers, "bad_snapshot", severe=True)
                    self._count_rejected("content")
                    self.pool.reject(key)
                elif e.retriable:
                    # content never disproven (advertisers gone/banned):
                    # drop it for now, but a re-discovered honest peer may
                    # re-advertise the same key later
                    self._count_rejected("no_peers")
                    self.pool.forget(key)
                else:
                    self._count_rejected("policy")
                    self.pool.reject(key)
            except ErrRetrySnapshot:
                logger.info("retrying snapshot %d/%d", key.height, key.format)
                self._count_rejected("retry")
            except ErrAbort:
                raise

    def _blame(self, peer_ids, reason: str, severe: bool = False) -> None:
        for pid in peer_ids:
            if self.scoreboard.record_failure(pid, reason, severe=severe):
                logger.warning("statesync peer %s banned (%s)",
                               pid[:8], reason)

    def _count_rejected(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.snapshots_rejected_total.labels(reason).inc()

    async def _sync(self, key: SnapshotKey):
        """(syncer.go Sync) one snapshot attempt."""
        self._current = key
        self.chunks = ChunkQueue(key.chunks)
        self._applied = 0
        self._attempts = {}
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        result = "rejected"
        try:
            out = await self._sync_inner(key)
            result = "restored"
            return out
        finally:
            if self.metrics is not None:
                self.metrics.restore_duration_seconds.labels(result).observe(
                    loop.time() - t0)

    async def _sync_inner(self, key: SnapshotKey):
        # fetch trusted app hash FIRST (stateprovider → light client): the
        # offer to the app carries it
        app_hash = await self.state_provider.app_hash(key.height)

        resp = self.app_snapshot.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(key.height, key.format, key.chunks,
                                   key.hash, self.pool.metadata.get(key, b"")),
            app_hash=app_hash))
        if resp.result == abci.OFFER_SNAPSHOT_REJECT:
            raise ErrSnapshotRejected("offer rejected")
        if resp.result == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            self.pool.reject_format(key.format)
            raise ErrSnapshotRejected("format rejected")
        if resp.result == abci.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted snapshot restore")
        if resp.result != abci.OFFER_SNAPSHOT_ACCEPT:
            raise ErrSnapshotRejected(f"unknown offer result {resp.result}")

        # parallel fetchers (syncer.go:415)
        fetchers = [asyncio.create_task(self._fetch_loop(key))
                    for _ in range(self.chunk_fetchers)]
        try:
            applied = 0
            while applied < key.chunks:
                if not self.chunks.has(applied):
                    if not self._eligible_peers(key):
                        # every advertiser is banned or gone: this snapshot
                        # can never complete — reject instead of wedging
                        raise ErrSnapshotRejected(
                            "no eligible peers left for snapshot",
                            retriable=True)
                    await self.chunks.wait_change(0.25)
                    continue
                chunk = self.chunks.get(applied)
                sender = self.chunks.sender(applied)
                if applied > 0:
                    # durability boundary (crashmatrix): >=1 chunk is in the
                    # app, the restore incomplete — a killed joiner must
                    # retry the restore from scratch, never trust the torso
                    from ..libs.fail import fail_point

                    fail_point("statesync.mid_chunk_apply")
                r = self.app_snapshot.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(
                        index=applied, chunk=chunk, sender=sender))
                if r.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT:
                    applied += 1
                    self._applied = applied
                    if sender:
                        self.scoreboard.record_success(sender)
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY:
                    self._discard(applied)
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT:
                    raise ErrRetrySnapshot("app requested snapshot retry")
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT:
                    # mid-restore data rejection (e.g. whole-blob hash vs
                    # the advertised hash): the advertised key was bad
                    raise ErrSnapshotRejected("app rejected snapshot",
                                              blame_advertisers=True)
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_ABORT:
                    raise ErrAbort("app aborted during chunk apply")
                for idx in r.refetch_chunks:
                    self._discard(idx)
                    if self.metrics is not None:
                        self.metrics.chunks_refetched_total.inc()
                for bad_sender in r.reject_senders:
                    # the app PROVED this sender served garbage (it
                    # verified the chunk against offered metadata) — ban it
                    # and drop everything it contributed
                    self._blame([bad_sender], "rejected_chunk", severe=True)
                    self.chunks.discard_sender(bad_sender)
                    if self.scoreboard.banned(bad_sender):
                        self.pool.remove_peer(bad_sender)
        finally:
            for f in fetchers:
                f.cancel()

        # verify the restored app against the trusted header (syncer.go:485)
        info = self.app_query.info(abci.RequestInfo())
        if info.last_block_app_hash != app_hash:
            raise ErrSnapshotRejected(
                f"restored app hash {info.last_block_app_hash.hex()} != trusted "
                f"{app_hash.hex()}", blame_advertisers=True)
        if info.last_block_height != key.height:
            raise ErrSnapshotRejected(
                f"restored app height {info.last_block_height} != {key.height}",
                blame_advertisers=True)

        state = await self.state_provider.state(key.height)
        commit = await self.state_provider.commit(key.height)
        logger.info("snapshot restored at height %d", key.height)
        return state, commit

    def _discard(self, idx: int) -> None:
        self.chunks.discard(idx)
        if self.metrics is not None:
            self.metrics.chunks_discarded_total.inc()

    # -- peer selection (deterministic, score-aware) -------------------------

    def _eligible_peers(self, key: SnapshotKey) -> List[str]:
        """Advertisers we may ask for a chunk right now, in the seeded
        rotation order. Backing-off peers are re-admitted as a last resort
        (better a slow peer than a wedged restore); banned peers never."""
        peers = self.pool.peers_of(key)
        order = self._rotation_order(peers)
        out = self.scoreboard.eligible(order)
        if not out:
            out = self.scoreboard.eligible(order, allow_backoff=True)
        return out

    def _rotation_order(self, peers: List[str]) -> List[str]:
        """One seeded shuffle per distinct peer set: deterministic for a
        given (seed, peer set), stable across retries so idx+attempt
        rotation walks every advertiser."""
        sig = tuple(peers)
        cached_sig, cached = self._order_cache
        if sig == cached_sig:
            return cached
        order = list(peers)
        self.rng.shuffle(order)
        self._order_cache = (sig, order)
        return order

    async def _fetch_loop(self, key: SnapshotKey) -> None:
        """One fetcher: allocate an index, ask the next peer in the seeded
        rotation, await arrival or re-allocate on timeout."""
        while True:
            idx = self.chunks.allocate()
            if idx is None:
                # never exit while the restore runs: a RETRY/refetch/reject
                # can discard chunks after completeness and needs a live
                # fetcher; cancellation (finally block in _sync) ends us
                await asyncio.sleep(0.1)
                continue
            peers = self._eligible_peers(key)
            if not peers:
                await asyncio.sleep(0.5)
                self._discard(idx)
                continue
            attempt = self._attempts.get(idx, 0)
            self._attempts[idx] = attempt + 1
            peer_id = peers[(idx + attempt) % len(peers)]
            if attempt > 0:
                self.scoreboard.note_retry()
            try:
                await self.request_chunk(peer_id, key.height, key.format, idx)
            except Exception:
                # the retry MUST yield: a request that fails synchronously
                # (every peer gone — e.g. the node was pulled from the net
                # mid-restore) would otherwise busy-spin this loop without
                # ever reaching an await, starving the event loop and making
                # the surrounding sync task uncancellable (found by
                # tools/crashmatrix.py's mid-chunk-apply kill)
                self._discard(idx)
                await asyncio.sleep(0.05)
                continue
            deadline = asyncio.get_running_loop().time() + self.chunk_timeout
            while not self.chunks.has(idx):
                if asyncio.get_running_loop().time() > deadline:
                    # a peer that never answers is indistinguishable from a
                    # malicious one at this layer: strike + backoff, and
                    # re-allocate the chunk elsewhere
                    self.scoreboard.record_failure(peer_id, "timeout")
                    self._discard(idx)
                    break
                await self.chunks.wait_change(0.25)
