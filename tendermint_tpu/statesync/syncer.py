"""Snapshot restore orchestration (reference statesync/syncer.go:145
SyncAny): pick a snapshot advertised by peers, OfferSnapshot to the app,
fetch chunks with parallel fetchers, ApplySnapshotChunk with
retry/refetch/reject semantics, and verify the restored app hash against a
light-client-obtained header.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from .chunks import ChunkQueue
from .stateprovider import StateProvider

logger = logging.getLogger("tmtpu.statesync")

CHUNK_FETCHERS = 4
CHUNK_REQUEST_TIMEOUT = 10.0


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    pass


class ErrSnapshotRejected(SyncError):
    pass


class ErrRetrySnapshot(SyncError):
    pass


class ErrAbort(SyncError):
    pass


@dataclass(frozen=True)
class SnapshotKey:
    height: int
    format: int
    chunks: int
    hash: bytes


@dataclass
class SnapshotPool:
    """Snapshots advertised by peers, best (highest, then format) first."""

    snapshots: Dict[SnapshotKey, Set[str]] = field(default_factory=dict)
    rejected: Set[SnapshotKey] = field(default_factory=set)
    metadata: Dict[SnapshotKey, bytes] = field(default_factory=dict)

    def add(self, peer_id: str, height: int, fmt: int, chunks: int,
            hash_: bytes, meta: bytes) -> bool:
        key = SnapshotKey(height, fmt, chunks, hash_)
        if key in self.rejected:
            return False
        new = key not in self.snapshots
        self.snapshots.setdefault(key, set()).add(peer_id)
        self.metadata[key] = meta
        return new

    def best(self) -> Optional[SnapshotKey]:
        cands = [k for k in self.snapshots if k not in self.rejected]
        if not cands:
            return None
        return max(cands, key=lambda k: (k.height, k.format))

    def reject(self, key: SnapshotKey) -> None:
        self.rejected.add(key)
        self.snapshots.pop(key, None)

    def reject_format(self, fmt: int) -> None:
        for k in list(self.snapshots):
            if k.format == fmt:
                self.reject(k)

    def remove_peer(self, peer_id: str) -> None:
        for k, peers in list(self.snapshots.items()):
            peers.discard(peer_id)
            if not peers:
                del self.snapshots[k]

    def peers_of(self, key: SnapshotKey) -> List[str]:
        return list(self.snapshots.get(key, ()))


class Syncer:
    """(syncer.go) Drives one snapshot restore against the app."""

    def __init__(self, proxy_snapshot, proxy_query, state_provider: StateProvider,
                 request_chunk, chunk_fetchers: int = CHUNK_FETCHERS,
                 chunk_timeout: float = CHUNK_REQUEST_TIMEOUT):
        self.app_snapshot = proxy_snapshot
        self.app_query = proxy_query
        self.state_provider = state_provider
        self.request_chunk = request_chunk  # async (peer_id, height, fmt, idx)
        self.pool = SnapshotPool()
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        self.chunks: Optional[ChunkQueue] = None
        self._current: Optional[SnapshotKey] = None

    def add_snapshot(self, peer_id: str, resp) -> bool:
        return self.pool.add(peer_id, resp.height, resp.format, resp.chunks,
                             resp.hash, resp.metadata)

    def add_chunk(self, resp, sender: str) -> None:
        cur = self._current
        if (self.chunks is None or cur is None
                or resp.height != cur.height or resp.format != cur.format):
            return
        if resp.missing:
            self.chunks.discard(resp.index)
            return
        self.chunks.add(resp.index, resp.chunk, sender)

    async def sync_any(self, discovery_time: float = 5.0):
        """(syncer.go:145 SyncAny) -> (state, commit) for the restored height.
        Tries snapshots best-first until one restores or none remain."""
        await asyncio.sleep(discovery_time)
        while True:
            key = self.pool.best()
            if key is None:
                raise ErrNoSnapshots("no viable snapshots remain")
            try:
                return await self._sync(key)
            except ErrSnapshotRejected:
                logger.info("snapshot %d/%d rejected; trying next",
                            key.height, key.format)
                self.pool.reject(key)
            except ErrRetrySnapshot:
                logger.info("retrying snapshot %d/%d", key.height, key.format)
            except ErrAbort:
                raise

    async def _sync(self, key: SnapshotKey):
        """(syncer.go Sync) one snapshot attempt."""
        self._current = key
        self.chunks = ChunkQueue(key.chunks)

        # fetch trusted app hash FIRST (stateprovider → light client): the
        # offer to the app carries it
        app_hash = await self.state_provider.app_hash(key.height)

        resp = self.app_snapshot.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(key.height, key.format, key.chunks,
                                   key.hash, self.pool.metadata.get(key, b"")),
            app_hash=app_hash))
        if resp.result == abci.OFFER_SNAPSHOT_REJECT:
            raise ErrSnapshotRejected("offer rejected")
        if resp.result == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            self.pool.reject_format(key.format)
            raise ErrSnapshotRejected("format rejected")
        if resp.result == abci.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted snapshot restore")
        if resp.result != abci.OFFER_SNAPSHOT_ACCEPT:
            raise ErrSnapshotRejected(f"unknown offer result {resp.result}")

        # parallel fetchers (syncer.go:415)
        fetchers = [asyncio.create_task(self._fetch_loop(key))
                    for _ in range(self.chunk_fetchers)]
        try:
            applied = 0
            while applied < key.chunks:
                if not self.chunks.has(applied):
                    await self.chunks.wait_change(0.25)
                    continue
                chunk = self.chunks.get(applied)
                r = self.app_snapshot.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(
                        index=applied, chunk=chunk,
                        sender=self.chunks.sender(applied)))
                if r.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT:
                    applied += 1
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY:
                    self.chunks.discard(applied)
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT:
                    raise ErrRetrySnapshot("app requested snapshot retry")
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT:
                    raise ErrSnapshotRejected("app rejected snapshot")
                elif r.result == abci.APPLY_SNAPSHOT_CHUNK_ABORT:
                    raise ErrAbort("app aborted during chunk apply")
                for idx in r.refetch_chunks:
                    self.chunks.discard(idx)
                for sender in r.reject_senders:
                    self.chunks.discard_sender(sender)
                    self.pool.remove_peer(sender)
        finally:
            for f in fetchers:
                f.cancel()

        # verify the restored app against the trusted header (syncer.go:485)
        info = self.app_query.info(abci.RequestInfo())
        if info.last_block_app_hash != app_hash:
            raise ErrSnapshotRejected(
                f"restored app hash {info.last_block_app_hash.hex()} != trusted "
                f"{app_hash.hex()}")
        if info.last_block_height != key.height:
            raise ErrSnapshotRejected(
                f"restored app height {info.last_block_height} != {key.height}")

        state = await self.state_provider.state(key.height)
        commit = await self.state_provider.commit(key.height)
        logger.info("snapshot restored at height %d", key.height)
        return state, commit

    async def _fetch_loop(self, key: SnapshotKey) -> None:
        """One fetcher: allocate an index, ask a random peer, await arrival
        or re-allocate on timeout."""
        import random

        while True:
            idx = self.chunks.allocate()
            if idx is None:
                # never exit while the restore runs: a RETRY/refetch/reject
                # can discard chunks after completeness and needs a live
                # fetcher; cancellation (finally block in _sync) ends us
                await asyncio.sleep(0.1)
                continue
            peers = self.pool.peers_of(key)
            if not peers:
                await asyncio.sleep(0.5)
                self.chunks.discard(idx)
                continue
            peer_id = random.choice(peers)
            try:
                await self.request_chunk(peer_id, key.height, key.format, idx)
            except Exception:
                self.chunks.discard(idx)
                continue
            deadline = asyncio.get_running_loop().time() + self.chunk_timeout
            while not self.chunks.has(idx):
                if asyncio.get_running_loop().time() > deadline:
                    self.chunks.discard(idx)  # re-allocate elsewhere
                    break
                await self.chunks.wait_change(0.25)
