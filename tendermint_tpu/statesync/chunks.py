"""Chunk queue for an in-flight snapshot restore
(reference statesync/chunks.go): dedup, per-chunk sender tracking,
allocation of next-to-fetch indexes, and refetch support."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Set


class ChunkQueue:
    def __init__(self, n_chunks: int):
        self.n_chunks = n_chunks
        self._chunks: Dict[int, bytes] = {}
        self._senders: Dict[int, str] = {}
        self._allocated: Set[int] = set()
        self._returned: Set[int] = set()
        self._event = asyncio.Event()

    def allocate(self) -> Optional[int]:
        """Next chunk index to fetch, or None when all are assigned."""
        for i in range(self.n_chunks):
            if i not in self._allocated and i not in self._chunks:
                self._allocated.add(i)
                return i
        return None

    def add(self, index: int, chunk: bytes, sender: str) -> bool:
        if not 0 <= index < self.n_chunks or index in self._chunks:
            return False
        self._chunks[index] = chunk
        self._senders[index] = sender
        self._event.set()
        return True

    def sender(self, index: int) -> str:
        return self._senders.get(index, "")

    def discard(self, index: int) -> None:
        """(chunks.go Discard) drop a chunk so it is refetched."""
        self._chunks.pop(index, None)
        self._senders.pop(index, None)
        self._allocated.discard(index)

    def discard_sender(self, sender: str) -> None:
        for i, s in list(self._senders.items()):
            if s == sender:
                self.discard(i)

    def retry_all(self) -> None:
        for i in list(self._chunks):
            self.discard(i)

    def has(self, index: int) -> bool:
        return index in self._chunks

    def get(self, index: int) -> Optional[bytes]:
        return self._chunks.get(index)

    def complete(self) -> bool:
        return len(self._chunks) == self.n_chunks

    async def wait_change(self, timeout: float) -> None:
        try:
            await asyncio.wait_for(self._event.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        self._event.clear()
