"""State-sync wire messages (reference proto/tendermint/statesync/types.proto,
statesync/messages.go): oneof {snapshots_request=1, snapshots_response=2,
chunk_request=3, chunk_response=4}."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protowire as pw


@dataclass
class SnapshotsRequest:
    pass


@dataclass
class SnapshotsResponse:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class ChunkRequest:
    height: int = 0
    format: int = 0
    index: int = 0


@dataclass
class ChunkResponse:
    height: int = 0
    format: int = 0
    index: int = 0
    chunk: bytes = b""
    missing: bool = False


def encode_msg(msg) -> bytes:
    w = pw.Writer()
    if isinstance(msg, SnapshotsRequest):
        w.message(1, b"")
    elif isinstance(msg, SnapshotsResponse):
        inner = pw.Writer()
        inner.varint(1, msg.height)
        inner.varint(2, msg.format)
        inner.varint(3, msg.chunks)
        inner.bytes(4, msg.hash)
        inner.bytes(5, msg.metadata)
        w.message(2, inner.finish())
    elif isinstance(msg, ChunkRequest):
        inner = pw.Writer()
        inner.varint(1, msg.height)
        inner.varint(2, msg.format)
        inner.varint(3, msg.index)
        w.message(3, inner.finish())
    elif isinstance(msg, ChunkResponse):
        inner = pw.Writer()
        inner.varint(1, msg.height)
        inner.varint(2, msg.format)
        inner.varint(3, msg.index)
        inner.bytes(4, msg.chunk)
        if msg.missing:
            inner.bool(5, True)
        w.message(4, inner.finish())
    else:
        raise TypeError(f"unknown statesync msg {type(msg)}")
    return w.finish()


def decode_msg(data: bytes):
    for fn, _wt, v in pw.iter_fields(data):
        f = pw.fields_dict(pw.as_bytes(v)) if fn != 1 else {}
        if fn == 1:
            return SnapshotsRequest()
        if fn == 2:
            return SnapshotsResponse(f.get(1, [0])[0], f.get(2, [0])[0],
                                     f.get(3, [0])[0], f.get(4, [b""])[0],
                                     f.get(5, [b""])[0])
        if fn == 3:
            return ChunkRequest(f.get(1, [0])[0], f.get(2, [0])[0],
                                f.get(3, [0])[0])
        if fn == 4:
            return ChunkResponse(f.get(1, [0])[0], f.get(2, [0])[0],
                                 f.get(3, [0])[0], f.get(4, [b""])[0],
                                 bool(f.get(5, [0])[0]))
    raise ValueError("empty statesync message")
