"""State providers: build a trusted sm.State + Commit at the snapshot height
(reference statesync/stateprovider.go:39 — backed by the light client over
2+ RPC servers).
"""

from __future__ import annotations

from typing import List, Optional

from ..light.client import LightClient, TrustOptions
from ..light.provider import HTTPProvider
from ..state.state import State
from ..types.block import Commit
from ..types.params import ConsensusParams


class StateProvider:
    async def app_hash(self, height: int) -> bytes:
        raise NotImplementedError

    async def commit(self, height: int) -> Commit:
        raise NotImplementedError

    async def state(self, height: int) -> State:
        raise NotImplementedError


class LightClientStateProvider(StateProvider):
    """(stateprovider.go lightClientStateProvider)

    Verifies headers via the light client (bisection from the trust root)
    and assembles the post-snapshot State the node boots consensus from.
    """

    def __init__(self, chain_id: str, genesis, rpc_clients: List,
                 trust_options: TrustOptions, scoreboard=None):
        if len(rpc_clients) < 2:
            raise ValueError("state sync needs >= 2 rpc servers "
                             "(primary + witness)")
        self.chain_id = chain_id
        self.genesis = genesis
        providers = [HTTPProvider(chain_id, c) for c in rpc_clients]
        # share the syncer's scoreboard (node.py passes it) so a diverging
        # witness and a lying chunk server count on the same ban series
        self.client = LightClient(chain_id, trust_options, providers[0],
                                  providers[1:], scoreboard=scoreboard)

    async def app_hash(self, height: int) -> bytes:
        """AppHash for `height` lives in header `height+1` (stateprovider.go)."""
        lb = await self.client.verify_light_block_at_height(height + 1)
        return lb.signed_header.header.app_hash

    async def commit(self, height: int) -> Commit:
        lb = await self.client.verify_light_block_at_height(height)
        return lb.signed_header.commit

    async def state(self, height: int) -> State:
        """(stateprovider.go State) needs headers h, h+1, h+2:
        h+1 carries AppHash + LastResultsHash, h+2's validators are
        NextValidators of h+1."""
        last = await self.client.verify_light_block_at_height(height)
        cur = await self.client.verify_light_block_at_height(height + 1)
        nxt = await self.client.verify_light_block_at_height(height + 2)
        state = State(
            last_validators=last.validator_set,
            chain_id=self.chain_id,
            initial_height=self.genesis.initial_height or 1,
            last_block_height=cur.signed_header.header.height - 1,
            last_block_id=cur.signed_header.header.last_block_id,
            last_block_time_ns=last.signed_header.header.time_ns,
            validators=cur.validator_set,
            next_validators=nxt.validator_set,
            last_height_validators_changed=cur.signed_header.header.height,
            consensus_params=self.genesis.consensus_params or ConsensusParams(),
            last_height_consensus_params_changed=self.genesis.initial_height or 1,
            app_hash=cur.signed_header.header.app_hash,
            last_results_hash=cur.signed_header.header.last_results_hash,
        )
        return state
