"""External API surface: JSON-RPC over HTTP + WebSocket subscriptions
(reference rpc/ — core route table rpc/core/routes.go:10-49, jsonrpc server
rpc/jsonrpc/server/, clients rpc/client/)."""

from .core import Environment  # noqa: F401
