"""JSON-RPC server: HTTP POST (JSON-RPC 2.0), GET URI routes, and the
/websocket subscription endpoint (reference rpc/jsonrpc/server/ —
http_json_handler.go, http_uri_handler.go, ws_handler.go:32).

aiohttp-based; one server per node, bound to config.rpc.laddr.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import time
from typing import Any, Dict, Optional

from aiohttp import WSCloseCode, WSMsgType, web

from .core import Environment, ROUTES, UNSAFE_ROUTES, RPCError

logger = logging.getLogger("tmtpu.rpc")


def _slow_ms_knob() -> float:
    """TMTPU_RPC_SLOW_MS: requests slower than this log one WARNING line
    with endpoint + latency (0 disables — the default; the load harness
    and incident debugging turn it on)."""
    try:
        return float(os.environ.get("TMTPU_RPC_SLOW_MS", "0") or 0)
    except ValueError:
        return 0.0


def _rpc_response(id_, result=None, error: Optional[RPCError] = None) -> Dict:
    if error is not None:
        return {"jsonrpc": "2.0", "id": id_,
                "error": {"code": error.code, "message": error.message,
                          "data": error.data}}
    return {"jsonrpc": "2.0", "id": id_, "result": result}


class RPCServer:
    def __init__(self, node):
        self.node = node
        self.env = Environment(node)
        self.metrics = None  # RPCMetrics, wired by the node
        self.slow_ms = _slow_ms_knob()
        self._runner: Optional[web.AppRunner] = None
        self._subscriptions: Dict[str, list] = {}  # ws id -> [sub ids]
        # one serialized payload per published event, shared across every
        # matching subscriber (see _event_fragment)
        self._ws_frag_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._routes = list(ROUTES)
        if getattr(node.config.rpc, "unsafe", False):
            self._routes += UNSAFE_ROUTES

    async def start(self, laddr: str) -> None:
        app = web.Application(client_max_size=self.node.config.rpc.max_body_bytes)
        app.router.add_post("/", self._handle_jsonrpc)
        app.router.add_get("/websocket", self._handle_websocket)
        for name in self._routes:
            app.router.add_get(f"/{name}", self._make_uri_handler(name))
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        host, port = _parse(laddr)
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.bound_port = self._runner.addresses[0][1] if self._runner.addresses else port
        logger.info("RPC listening on %s:%s", host, self.bound_port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- JSON-RPC POST -------------------------------------------------------

    async def _handle_jsonrpc(self, request: web.Request) -> web.Response:
        raw = await request.read()
        if self.metrics is not None:
            self.metrics.request_size_bytes.observe(len(raw))
        try:
            body = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return self._json_response(
                _rpc_response(None, error=RPCError(-32700, "parse error")),
                status=500)
        single = not isinstance(body, list)
        reqs = [body] if single else body
        out = []
        for r in reqs:
            out.append(await self._dispatch(r))
        return self._json_response(out[0] if single else out)

    def _json_response(self, payload, status: int = 200) -> web.Response:
        """One serialization pass — the response-size histogram observes
        the exact bytes that go on the wire."""
        text = json.dumps(payload)
        if self.metrics is not None:
            self.metrics.response_size_bytes.observe(len(text))
        return web.Response(text=text, status=status,
                            content_type="application/json")

    async def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """The single funnel for POST, GET-URI, and websocket-carried
        METHOD calls — instrumented once here so those entry paths share
        the per-endpoint latency/outcome series and the in-flight gauge.
        Websocket subscription management (subscribe/unsubscribe) is
        handled inline in the ws loop and is visible through the
        websocket_subscribers gauge instead."""
        method = req.get("method", "")
        # unknown methods share one label: a port scan or fuzzing client
        # must not mint unbounded series on the registry
        endpoint = method if method in self._routes else "unknown"
        m = self.metrics
        t0 = time.perf_counter()
        if m is not None:
            m.requests_in_flight.inc()
        try:
            resp = await self._dispatch_inner(req, method)
        finally:
            if m is not None:
                m.requests_in_flight.inc(-1)
        elapsed = time.perf_counter() - t0
        if m is not None:
            outcome = "error" if "error" in resp else "ok"
            m.request_seconds.labels(endpoint, outcome).observe(elapsed)
        if self.slow_ms > 0 and elapsed * 1000.0 >= self.slow_ms:
            logger.warning("slow rpc %s took %.1f ms (threshold %.0f ms)",
                           endpoint, elapsed * 1000.0, self.slow_ms)
        return resp

    async def _dispatch_inner(self, req: Dict[str, Any],
                              method: str) -> Dict[str, Any]:
        id_ = req.get("id")
        params = req.get("params") or {}
        if method not in self._routes:
            return _rpc_response(id_, error=RPCError(-32601,
                                                     f"method {method!r} not found"))
        handler = getattr(self.env, method)
        try:
            if isinstance(params, list):
                result = await handler(*params)
            else:
                result = await handler(**params)
            return _rpc_response(id_, result=result)
        except RPCError as e:
            return _rpc_response(id_, error=e)
        except TypeError as e:
            return _rpc_response(id_, error=RPCError(-32602, f"invalid params: {e}"))
        except Exception as e:
            logger.exception("rpc %s failed", method)
            return _rpc_response(id_, error=RPCError(-32603, str(e)))

    # -- GET URI -------------------------------------------------------------

    def _make_uri_handler(self, name: str):
        async def handler(request: web.Request) -> web.Response:
            if self.metrics is not None:
                self.metrics.request_size_bytes.observe(
                    len(request.path_qs))
            params = {}
            for k, v in request.query.items():
                params[k] = _coerce(k, v)
            fake = {"id": -1, "method": name, "params": params}
            return self._json_response(await self._dispatch(fake))
        return handler

    # -- WebSocket subscriptions (ws_handler.go:32) --------------------------

    async def _handle_websocket(self, request: web.Request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        ws_id = f"ws-{id(ws)}"
        pumps: list = []
        fan = _WsFanout(
            ws, getattr(self.node.config.rpc, "ws_send_queue_size", 256),
            on_evict=self._count_ws_eviction)
        if self.metrics is not None:
            self.metrics.websocket_subscribers.inc()
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    req = json.loads(msg.data)
                except json.JSONDecodeError:
                    continue
                method = req.get("method")
                id_ = req.get("id")
                params = req.get("params") or {}
                if method == "subscribe":
                    query = params.get("query", "")
                    sub = self.node.event_bus.subscribe(ws_id, query)
                    fan.enqueue(json.dumps(_rpc_response(id_, result={})))
                    pumps.append(asyncio.create_task(
                        self._pump(fan, id_, query, sub)))
                elif method == "unsubscribe_all" or method == "unsubscribe":
                    _quiet_unsubscribe(self.node.event_bus, ws_id)
                    fan.enqueue(json.dumps(_rpc_response(id_, result={})))
                else:
                    fan.enqueue(json.dumps(await self._dispatch(req)))
        finally:
            if self.metrics is not None:
                self.metrics.websocket_subscribers.inc(-1)
            _quiet_unsubscribe(self.node.event_bus, ws_id)
            for p in pumps:
                p.cancel()
            fan.stop()
        return ws

    def _count_ws_eviction(self) -> None:
        if self.metrics is not None:
            self.metrics.ws_slow_consumer_evictions_total.inc()

    def _event_fragment(self, msg) -> str:
        """ONE serialized ``{"data": ..., "events": ...}`` payload per
        published event, shared across every matching subscriber: pubsub
        delivers the same Message object to each subscription, so the
        fragment caches on its identity (the strong ref in the cache keeps
        the id stable); _render_ws_frame wraps it per-subscription."""
        key = id(msg)
        hit = self._ws_frag_cache.get(key)
        if hit is not None and hit[0] is msg:
            return hit[1]
        frag = json.dumps({"data": _encode_event_data(msg.data),
                           "events": msg.events})
        self._ws_frag_cache[key] = (msg, frag)
        while len(self._ws_frag_cache) > 64:
            self._ws_frag_cache.popitem(last=False)
        return frag

    async def _pump(self, fan: "_WsFanout", id_, query: str, sub) -> None:
        from ..libs.pubsub import SubscriptionCanceled

        try:
            while True:
                msg = await sub.next()
                fan.enqueue(_render_ws_frame(id_, query,
                                             self._event_fragment(msg)))
                if fan.evicted:
                    return
        except (SubscriptionCanceled, ConnectionError, asyncio.CancelledError):
            pass


class _WsFanout:
    """Per-socket bounded send queue with one sender task.

    The old pump awaited each ``ws.send_json`` inline with no bound: one
    stalled reader back-pressured the event bus for everyone. Now frames
    are enqueued; a full queue EVICTS the socket — explicit close
    (TRY_AGAIN_LATER) counted on rpc_ws_slow_consumer_evictions_total —
    instead of stalling. The ws argument is duck-typed (send_str/close)
    so the regression test can inject a never-reading socket."""

    def __init__(self, ws, maxsize: int, on_evict=None):
        self.ws = ws
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=max(1, int(maxsize)))
        self.evicted = False
        self._on_evict = on_evict
        self._sender = asyncio.get_running_loop().create_task(self._drain())

    async def _drain(self) -> None:
        try:
            while True:
                text = await self.queue.get()
                await self.ws.send_str(text)
        except (ConnectionError, RuntimeError, asyncio.CancelledError):
            pass

    def enqueue(self, text: str) -> bool:
        """Queue a frame; on overflow evict the socket. Returns False when
        the frame was dropped (socket already evicted or overflowing)."""
        if self.evicted:
            return False
        try:
            self.queue.put_nowait(text)
            return True
        except asyncio.QueueFull:
            self.evicted = True
            if self._on_evict is not None:
                self._on_evict()
            self._sender.cancel()
            asyncio.get_running_loop().create_task(self._close())
            return False

    async def _close(self) -> None:
        try:
            await self.ws.close(code=WSCloseCode.TRY_AGAIN_LATER,
                                message=b"slow consumer")
        except Exception:
            pass

    def stop(self) -> None:
        self._sender.cancel()


def _render_ws_frame(id_, query: str, fragment: str) -> str:
    """Assemble a subscription frame around a shared pre-serialized
    ``{"data": ..., "events": ...}`` fragment. MUST stay byte-identical to
    ``json.dumps(_rpc_response(id_, result={"query": query, "data": ...,
    "events": ...}))`` — pinned by the ws frame parity test."""
    return ('{"jsonrpc": "2.0", "id": %s, "result": {"query": %s, %s}'
            % (json.dumps(id_), json.dumps(query), fragment[1:]))


def _quiet_unsubscribe(bus, subscriber: str) -> None:
    try:
        bus.unsubscribe_all(subscriber)
    except ValueError:
        pass  # never subscribed


def _encode_event_data(data) -> Dict[str, Any]:
    from .json_enc import enc_block, enc_tx_result, b64
    from ..types.event_bus import EventDataNewBlock, EventDataTx

    if isinstance(data, EventDataNewBlock):
        return {"type": "tendermint/event/NewBlock",
                "value": {"block": enc_block(data.block)}}
    if isinstance(data, EventDataTx):
        return {"type": "tendermint/event/Tx",
                "value": {"TxResult": {
                    "height": str(data.height), "index": data.index,
                    "tx": b64(data.tx), "result": enc_tx_result(data.result)}}}
    return {"type": type(data).__name__, "value": {}}


# URI params that are numeric; everything else stays a string (a hex "data"
# param must not be swallowed by int())
_NUMERIC_PARAMS = {"height", "page", "per_page", "limit", "min_height",
                   "max_height", "trusted_height", "trust_num", "trust_den"}


def _coerce(key: str, v: str):
    if v in ("true", "false"):
        return v == "true"
    if v.startswith('"') and v.endswith('"'):
        return v[1:-1]
    if key in _NUMERIC_PARAMS:
        try:
            return int(v)
        except ValueError:
            return v
    return v


def _parse(laddr: str):
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
