"""gRPC BroadcastAPI (reference rpc/grpc/{api.go,types.pb.go}).

Deprecated upstream in favor of the JSON-RPC interface but still served for
wire parity: service ``tendermint.rpc.grpc.BroadcastAPI`` with

* ``Ping(RequestPing) -> ResponsePing`` — both empty messages;
* ``BroadcastTx(RequestBroadcastTx{tx bytes=1}) ->
  ResponseBroadcastTx{check_tx=1, deliver_tx=2}`` — delegates to the
  JSON-RPC environment's ``broadcast_tx_commit`` exactly like the
  reference's broadcastAPI (api.go:29 calls core.BroadcastTxCommit).

Bodies reuse the hand-rolled gogoproto-exact ABCI codec for the embedded
ResponseCheckTx/ResponseDeliverTx messages; no generated stubs.
"""

from __future__ import annotations

import asyncio
import base64
import logging
from concurrent import futures
from typing import Optional

import grpc

from ..abci import types as abci
from ..abci.proto_codec import _dec_response_body, _enc_response_body
from ..libs import protowire as pw

logger = logging.getLogger("tmtpu.rpc.grpc")

SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


def _enc_request_broadcast_tx(tx: bytes) -> bytes:
    w = pw.Writer()
    w.bytes(1, tx)
    return w.finish()


def _dec_request_broadcast_tx(raw: bytes) -> bytes:
    for fn, _wt, v in pw.iter_fields(raw):
        if fn == 1:
            return v
    return b""


def _result_to_abci(doc: dict, cls):
    """JSON-RPC tx-result doc -> abci Response{Check,Deliver}Tx."""
    return cls(
        code=int(doc.get("code", 0)),
        data=base64.b64decode(doc["data"]) if doc.get("data") else b"",
        log=doc.get("log", ""),
        gas_wanted=int(doc.get("gas_wanted", 0) or 0),
        gas_used=int(doc.get("gas_used", 0) or 0),
    )


def _enc_response_broadcast_tx(check: abci.ResponseCheckTx,
                               deliver: abci.ResponseDeliverTx) -> bytes:
    w = pw.Writer()
    w.message(1, _enc_response_body("check_tx", check))
    w.message(2, _enc_response_body("deliver_tx", deliver))
    return w.finish()


def _dec_response_broadcast_tx(raw: bytes):
    check = deliver = None
    for fn, _wt, v in pw.iter_fields(raw):
        if fn == 1:
            check = _dec_response_body("check_tx", v)
        elif fn == 2:
            deliver = _dec_response_body("deliver_tx", v)
    return check, deliver


class BroadcastAPIServer:
    """Serves BroadcastAPI next to the JSON-RPC server; calls into the same
    Environment on the node's asyncio loop (the gRPC worker threads bridge
    with run_coroutine_threadsafe)."""

    def __init__(self, addr: str, env, loop: asyncio.AbstractEventLoop,
                 max_workers: int = 2):
        self._env = env
        self._loop = loop
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.port = self._server.add_insecure_port(addr)

    def _handler(self) -> grpc.GenericRpcHandler:
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method.rsplit("/", 1)[-1]
                if not handler_call_details.method.startswith(f"/{SERVICE}/"):
                    return None
                if name == "Ping":
                    return grpc.unary_unary_rpc_method_handler(
                        lambda req, ctx: b"",
                        request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                if name == "BroadcastTx":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._broadcast_tx,
                        request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                return None

        return Handler()

    def _broadcast_tx(self, req_bytes: bytes, context) -> bytes:
        tx = _dec_request_broadcast_tx(req_bytes)
        fut = asyncio.run_coroutine_threadsafe(
            self._env.broadcast_tx_commit(base64.b64encode(tx).decode()),
            self._loop)
        try:
            doc = fut.result(timeout=60.0)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return b""
        check = _result_to_abci(doc.get("check_tx", {}), abci.ResponseCheckTx)
        deliver = _result_to_abci(doc.get("deliver_tx", {}),
                                  abci.ResponseDeliverTx)
        return _enc_response_broadcast_tx(check, deliver)

    def start(self) -> None:
        self._server.start()
        logger.info("gRPC BroadcastAPI on port %d", self.port)

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace)


class BroadcastAPIClient:
    def __init__(self, addr: str, timeout: float = 60.0):
        self._chan = grpc.insecure_channel(addr)
        self._timeout = timeout

    def ping(self) -> None:
        fn = self._chan.unary_unary(f"/{SERVICE}/Ping",
                                    request_serializer=lambda b: b,
                                    response_deserializer=lambda b: b)
        fn(b"", timeout=self._timeout)

    def broadcast_tx(self, tx: bytes):
        fn = self._chan.unary_unary(f"/{SERVICE}/BroadcastTx",
                                    request_serializer=lambda b: b,
                                    response_deserializer=lambda b: b)
        raw = fn(_enc_request_broadcast_tx(tx), timeout=self._timeout)
        return _dec_response_broadcast_tx(raw)

    def close(self) -> None:
        self._chan.close()
