"""JSON encoding of domain types for the RPC surface.

Follows the reference's conventions (rpc/core responses rendered through
tmjson): hashes/addresses as upper-hex strings, binary payloads (txs, app
data) as base64, heights/numbers as decimal strings, timestamps as RFC3339.
"""

from __future__ import annotations

import base64
import datetime
from typing import Any, Dict, Optional

from ..types.block import Block, Commit, Header
from ..types.basic import BlockID
from ..types.validator import Validator


def b64(b: bytes) -> str:
    return base64.b64encode(b or b"").decode()


def hexu(b: bytes) -> str:
    return (b or b"").hex().upper()


def rfc3339(ns: int) -> str:
    """Nanosecond-precision RFC3339 (Go time.RFC3339Nano shape): header times
    are ns-exact and MUST round-trip, or recomputed header hashes diverge."""
    secs, frac = divmod(ns, 1_000_000_000)
    dt = datetime.datetime.fromtimestamp(secs, tz=datetime.timezone.utc)
    # strftime leaves year 1 (Go zero time) unpadded — pad explicitly so
    # the string stays ISO-parseable on the way back in
    return (f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}"
            f"T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}.{frac:09d}Z")


def enc_block_id(bid: Optional[BlockID]) -> Dict[str, Any]:
    if bid is None:
        return {"hash": "", "parts": {"total": 0, "hash": ""}}
    return {
        "hash": hexu(bid.hash),
        "parts": {"total": bid.part_set_header.total,
                  "hash": hexu(bid.part_set_header.hash)},
    }


def enc_header(h: Header) -> Dict[str, Any]:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": rfc3339(h.time_ns),
        "last_block_id": enc_block_id(h.last_block_id),
        "last_commit_hash": hexu(h.last_commit_hash),
        "data_hash": hexu(h.data_hash),
        "validators_hash": hexu(h.validators_hash),
        "next_validators_hash": hexu(h.next_validators_hash),
        "consensus_hash": hexu(h.consensus_hash),
        "app_hash": hexu(h.app_hash),
        "last_results_hash": hexu(h.last_results_hash),
        "evidence_hash": hexu(h.evidence_hash),
        "proposer_address": hexu(h.proposer_address),
    }


def enc_commit(c: Optional[Commit]) -> Optional[Dict[str, Any]]:
    if c is None:
        return None
    if hasattr(c, "agg_sig"):
        return {
            "height": str(c.height),
            "round": c.round,
            "block_id": enc_block_id(c.block_id),
            "aggregated_signature": {
                "signers": "".join("1" if c.signers.get_index(i) else "0"
                                   for i in range(c.signers.size())),
                "signature": b64(c.agg_sig),
                "timestamp": rfc3339(c.timestamp_ns),
            },
        }
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": enc_block_id(c.block_id),
        "signatures": [
            {
                "block_id_flag": int(s.block_id_flag),
                "validator_address": hexu(s.validator_address),
                "timestamp": rfc3339(s.timestamp_ns),
                "signature": b64(s.signature),
            }
            for s in c.signatures
        ],
    }


def enc_vote(v) -> Dict[str, Any]:
    return {
        "type": int(v.type),
        "height": str(v.height),
        "round": int(v.round),
        "block_id": enc_block_id(v.block_id),
        "timestamp": rfc3339(v.timestamp_ns),
        "validator_address": hexu(v.validator_address),
        "validator_index": int(v.validator_index),
        "signature": b64(v.signature),
    }


def enc_evidence(ev) -> Dict[str, Any]:
    """(types/evidence.go json shapes; DuplicateVoteEvidence is the one the
    e2e byzantine invariant scans for)"""
    kind = type(ev).__name__
    if kind == "DuplicateVoteEvidence":
        return {
            "type": "tendermint/DuplicateVoteEvidence",
            "value": {
                "vote_a": enc_vote(ev.vote_a),
                "vote_b": enc_vote(ev.vote_b),
                "total_voting_power": str(getattr(ev, "total_voting_power", 0)),
                "validator_power": str(getattr(ev, "validator_power", 0)),
                "timestamp": rfc3339(ev.timestamp_ns),
            },
        }
    return {"type": f"tendermint/{kind}",
            "value": {"height": str(getattr(ev, "height", 0))}}


def enc_block(b: Block) -> Dict[str, Any]:
    return {
        "header": enc_header(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": [enc_evidence(e) for e in b.evidence]},
        "last_commit": enc_commit(b.last_commit),
    }


_PUBKEY_JSON_TYPES = {
    "ed25519": "tendermint/PubKeyEd25519",
    "secp256k1": "tendermint/PubKeySecp256k1",
    "sr25519": "tendermint/PubKeySr25519",
    "bls12381": "tendermint/PubKeyBls12381",
}


def enc_validator(v: Validator) -> Dict[str, Any]:
    return {
        "address": hexu(v.address),
        "pub_key": {"type": _PUBKEY_JSON_TYPES.get(v.pub_key.type_name,
                                                   "tendermint/PubKeyEd25519"),
                    "value": b64(v.pub_key.bytes())},
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def enc_tx_result(r) -> Dict[str, Any]:
    return {
        "code": getattr(r, "code", 0),
        "data": b64(getattr(r, "data", b"")),
        "log": getattr(r, "log", ""),
        "info": getattr(r, "info", ""),
        "gas_wanted": str(getattr(r, "gas_wanted", 0)),
        "gas_used": str(getattr(r, "gas_used", 0)),
        "events": [],
        "codespace": getattr(r, "codespace", ""),
    }
