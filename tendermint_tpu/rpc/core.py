"""Core RPC route handlers over node internals
(reference rpc/core/ — route table routes.go:10-49, env.go Environment).

Every handler is an async method returning a JSON-serializable dict; the
server layer (server.py) maps JSON-RPC / URI calls onto them, and the local
client (client.py LocalClient) calls them directly in-proc (the reference's
rpc/client/local pattern, used by tests and the light-client provider).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Any, Dict, List, Optional

from ..types import events as tme
from .json_enc import (
    b64,
    enc_block,
    enc_block_id,
    enc_commit,
    enc_header,
    enc_tx_result,
    enc_validator,
    hexu,
    rfc3339,
)


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


class Environment:
    """(rpc/core/env.go) Handlers reach node internals through this."""

    def __init__(self, node):
        self.node = node

    # -- info routes ---------------------------------------------------------

    async def health(self) -> Dict[str, Any]:
        return {}

    async def status(self) -> Dict[str, Any]:
        """(rpc/core/status.go)"""
        node = self.node
        latest_height = node.block_store.height()
        meta = node.block_store.load_block_meta(latest_height)
        earliest = node.block_store.base()
        emeta = node.block_store.load_block_meta(earliest)
        pub = None
        if node.priv_validator is not None:
            pub = node.priv_validator.get_pub_key()
        cs = node.consensus_state
        return {
            "node_info": {
                "id": node.node_key.id,
                "listen_addr": node.node_info.listen_addr,
                "network": node.genesis.chain_id,
                "version": node.node_info.version,
                "moniker": node.config.base.moniker,
                "protocol_version": {
                    "p2p": str(node.node_info.protocol_p2p),
                    "block": str(node.node_info.protocol_block),
                    "app": str(node.node_info.protocol_app),
                },
            },
            "sync_info": {
                "latest_block_hash": hexu(meta.block_id.hash if meta else b""),
                "latest_app_hash": hexu(cs.state.app_hash),
                "latest_block_height": str(latest_height),
                "latest_block_time": (rfc3339(meta.header.time_ns)
                                      if meta else ""),
                "earliest_block_height": str(earliest),
                "earliest_block_hash": hexu(emeta.block_id.hash if emeta else b""),
                "catching_up": not node.blockchain_reactor.synced.is_set()
                if node._fast_sync else False,
            },
            "validator_info": {
                "address": hexu(pub.address()) if pub else "",
                "pub_key": {"type": "tendermint/PubKeyEd25519",
                            "value": b64(pub.bytes())} if pub else None,
                "voting_power": str(self._voting_power(pub)),
            },
        }

    def _voting_power(self, pub) -> int:
        if pub is None:
            return 0
        vals = self.node.consensus_state.state.validators
        idx, val = vals.get_by_address(pub.address())
        return val.voting_power if val else 0

    async def net_info(self) -> Dict[str, Any]:
        sw = self.node.switch
        peers = []
        for p in sw.peers.values():
            info = getattr(p, "node_info", None)
            peers.append({
                "node_info": {
                    "id": p.id,
                    "moniker": getattr(info, "moniker", ""),
                    "network": getattr(info, "network", ""),
                    "listen_addr": getattr(info, "listen_addr", ""),
                },
                "is_outbound": p.outbound,
                "remote_ip": getattr(getattr(p, "socket_addr", None), "host", ""),
            })
        return {
            "listening": sw.transport is not None,
            "listeners": [str(self.node.listen_addr)] if self.node.listen_addr else [],
            "n_peers": str(len(peers)),
            "peers": peers,
        }

    async def genesis(self) -> Dict[str, Any]:
        import json as _json

        return {"genesis": _json.loads(self.node.genesis.to_json())}

    # -- blockchain routes ---------------------------------------------------

    def _height_or_latest(self, height: Optional[int]) -> int:
        store = self.node.block_store
        if height is None or int(height) <= 0:
            return store.height()
        h = int(height)
        if h > store.height():
            raise RPCError(-32603, f"height {h} must be <= {store.height()}")
        if h < store.base():
            raise RPCError(-32603, f"height {h} is below base {store.base()}")
        return h

    async def blockchain(self, min_height: int = 0, max_height: int = 0
                         ) -> Dict[str, Any]:
        """(rpc/core/blocks.go BlockchainInfo) newest-first headers, cap 20."""
        store = self.node.block_store
        maxh = int(max_height) or store.height()
        maxh = min(maxh, store.height())
        minh = max(int(min_height) or store.base(), store.base())
        minh = max(minh, maxh - 19)
        metas = []
        for h in range(maxh, minh - 1, -1):
            m = store.load_block_meta(h)
            if m is None:
                continue
            metas.append({
                "block_id": enc_block_id(m.block_id),
                "block_size": str(m.block_size),
                "header": enc_header(m.header),
                "num_txs": str(m.num_txs),
            })
        return {"last_height": str(store.height()), "block_metas": metas}

    async def block(self, height: Optional[int] = None) -> Dict[str, Any]:
        h = self._height_or_latest(height)
        blk = self.node.block_store.load_block(h)
        meta = self.node.block_store.load_block_meta(h)
        if blk is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {"block_id": enc_block_id(meta.block_id), "block": enc_block(blk)}

    async def block_by_hash(self, hash: str) -> Dict[str, Any]:
        blk = self.node.block_store.load_block_by_hash(bytes.fromhex(hash))
        if blk is None:
            return {"block_id": enc_block_id(None), "block": None}
        meta = self.node.block_store.load_block_meta(blk.header.height)
        return {"block_id": enc_block_id(meta.block_id), "block": enc_block(blk)}

    async def commit(self, height: Optional[int] = None) -> Dict[str, Any]:
        """(rpc/core/blocks.go Commit) header + its canonical commit."""
        h = self._height_or_latest(height)
        store = self.node.block_store
        meta = store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no header at height {h}")
        if h == store.height():
            commit = store.load_seen_commit(h)
            canonical = False
        else:
            commit = store.load_block_commit(h)
            canonical = True
        return {
            "signed_header": {"header": enc_header(meta.header),
                              "commit": enc_commit(commit)},
            "canonical": canonical,
        }

    async def block_results(self, height: Optional[int] = None) -> Dict[str, Any]:
        h = self._height_or_latest(height)
        resp = self.node.state_store.load_abci_responses(h)
        if resp is None:
            raise RPCError(-32603, f"no results for height {h}")
        return {
            "height": str(h),
            "txs_results": [enc_tx_result(r) for r in resp.deliver_txs],
            "begin_block_events": [],
            "end_block_events": [],
            "validator_updates": [],
            "consensus_param_updates": None,
        }

    async def validators(self, height: Optional[int] = None, page: int = 1,
                         per_page: int = 30) -> Dict[str, Any]:
        h = self._height_or_latest(height)
        vals = self.node.state_store.load_validators(h)
        if vals is None:
            raise RPCError(-32603, f"no validator set at height {h}")
        allv = vals.validators
        page, per_page = max(1, int(page)), min(100, int(per_page))
        start = (page - 1) * per_page
        sel = allv[start:start + per_page]
        return {
            "block_height": str(h),
            "validators": [enc_validator(v) for v in sel],
            "count": str(len(sel)),
            "total": str(len(allv)),
        }

    async def consensus_state(self) -> Dict[str, Any]:
        rs = self.node.consensus_state.rs
        return {"round_state": {
            "height/round/step": f"{rs.height}/{rs.round}/{int(rs.step)}",
            "height": str(rs.height), "round": rs.round, "step": int(rs.step),
            "proposal_block_hash": hexu(
                rs.proposal_block.hash() if rs.proposal_block else b""),
        }}

    async def dump_consensus_state(self) -> Dict[str, Any]:
        """(rpc/core/consensus.go DumpConsensusState) full round state with
        vote bit-arrays + per-peer round states — the wedged-net diagnostic."""
        cs = self.node.consensus_state
        rs = cs.rs
        votes = []
        if rs.votes is not None:
            for r in range(rs.round + 1):
                pv = rs.votes.prevotes(r)
                pc = rs.votes.precommits(r)
                votes.append({
                    "round": r,
                    "prevotes": str(pv.bit_array()) if pv else "nil",
                    "prevotes_bit_array": str(pv.bit_array()) if pv else "",
                    "precommits": str(pc.bit_array()) if pc else "nil",
                    "precommits_bit_array": str(pc.bit_array()) if pc else "",
                })
        round_state = {
            "height": str(rs.height), "round": rs.round, "step": int(rs.step),
            "start_time": rfc3339(rs.start_time_ns),
            "commit_time": rfc3339(rs.commit_time_ns),
            "proposal": ({"height": str(rs.proposal.height),
                          "round": rs.proposal.round,
                          "pol_round": rs.proposal.pol_round}
                         if rs.proposal else None),
            "proposal_block_hash": hexu(
                rs.proposal_block.hash() if rs.proposal_block else b""),
            "locked_round": rs.locked_round,
            "locked_block_hash": hexu(
                rs.locked_block.hash() if rs.locked_block else b""),
            "valid_round": rs.valid_round,
            "valid_block_hash": hexu(
                rs.valid_block.hash() if rs.valid_block else b""),
            "height_vote_set": votes,
            "triggered_timeout_precommit": rs.triggered_timeout_precommit,
        }
        peers = []
        reactor = getattr(self.node, "consensus_reactor", None)
        for pid, ps in (getattr(reactor, "_peer_states", {}) or {}).items():
            prs = getattr(ps, "prs", None)
            peers.append({
                "node_address": pid,
                "peer_state": {
                    "height": str(getattr(prs, "height", 0)),
                    "round": getattr(prs, "round", -1),
                    "step": int(getattr(prs, "step", 0) or 0),
                } if prs is not None else None,
            })
        return {"round_state": round_state, "peers": peers}

    async def consensus_stage_timeline(self, limit: int = 20) -> Dict[str, Any]:
        """Per-height consensus stage timeline tail (consensus/timeline.py):
        the newest ``limit`` sealed heights' stage marks and durations plus
        the in-flight height — the RPC view of the bounded in-memory ring
        the stage_seconds histograms are derived from."""
        tl = getattr(self.node.consensus_state, "timeline", None)
        if tl is None:
            return {"capacity": 0, "heights_sealed": 0,
                    "current": None, "heights": []}
        return tl.snapshot(int(limit))

    async def tx_timeline(self, limit: int = 20) -> Dict[str, Any]:
        """Per-tx lifecycle timeline tail (libs/txlife.py): the newest
        ``limit`` sealed records — stage stamps from rpc_received through
        committed/rejected — plus the tracker's sampling/bounds config.
        The RPC view the open-loop load harness (tools/loadtime.py)
        scrapes for in-node end-to-end latency truth."""
        tl = getattr(self.node.mempool, "txlife", None)
        if tl is None:
            return {"enabled": False, "sample_rate": 0.0, "active": 0,
                    "sealed_total": 0, "records": []}
        return tl.snapshot(int(limit))

    async def check_tx(self, tx: str = "") -> Dict[str, Any]:
        """(rpc/core/mempool.go CheckTx route) run CheckTx against the app
        WITHOUT adding to the mempool."""
        from ..abci import types as abci

        raw = _decode_tx_param(tx)
        resp = self.node.proxy_app.mempool.check_tx(
            abci.RequestCheckTx(tx=raw))
        return {
            "code": resp.code, "data": b64(getattr(resp, "data", b"")),
            "log": resp.log, "info": getattr(resp, "info", ""),
            "gas_wanted": str(resp.gas_wanted),
            "gas_used": str(getattr(resp, "gas_used", 0)),
            "codespace": getattr(resp, "codespace", ""),
        }

    async def genesis_chunked(self, chunk: int = 0) -> Dict[str, Any]:
        """(rpc/core/net.go GenesisChunked) base64 chunks of the genesis doc
        for genesis files too large for one response."""
        import base64 as _b64

        doc = self.node.genesis.to_json().encode()
        size = 16 * 1024 * 1024
        chunks = [doc[i:i + size] for i in range(0, max(len(doc), 1), size)]
        c = int(chunk)
        if not 0 <= c < len(chunks):
            raise RPCError(-32602, f"chunk {c} out of range 0..{len(chunks)-1}")
        return {"chunk": str(c), "total": str(len(chunks)),
                "data": _b64.b64encode(chunks[c]).decode()}

    # -- unsafe routes (routes.go:52; served only with rpc.unsafe) -----------

    @staticmethod
    def _addr_list(value) -> str:
        """Accept a JSON list or a single comma-separated string (the URI
        GET interface always delivers one string)."""
        if value is None:
            return ""
        if isinstance(value, str):
            return value
        return ",".join(value)

    async def dial_seeds(self, seeds=None) -> Dict[str, Any]:
        from ..p2p import parse_peer_list

        self.node.switch.dial_peers_async(
            parse_peer_list(self._addr_list(seeds)))
        return {"log": f"dialing seeds: {seeds}"}

    async def dial_peers(self, peers=None,
                         persistent: bool = False) -> Dict[str, Any]:
        from ..p2p import parse_peer_list

        self.node.switch.dial_peers_async(
            parse_peer_list(self._addr_list(peers)),
            persistent=bool(persistent))
        return {"log": f"dialing peers: {peers}"}

    async def unsafe_flush_mempool(self) -> Dict[str, Any]:
        self.node.mempool.flush()
        return {}

    async def consensus_params(self, height: Optional[int] = None) -> Dict[str, Any]:
        h = self._height_or_latest(height)
        params = self.node.state_store.load_consensus_params(h)
        if params is None:
            params = self.node.consensus_state.state.consensus_params
        return {"block_height": str(h), "consensus_params": {
            "block": {"max_bytes": str(params.block.max_bytes),
                      "max_gas": str(params.block.max_gas)},
            "evidence": {"max_age_num_blocks": str(params.evidence.max_age_num_blocks)},
        }}

    # -- ABCI ----------------------------------------------------------------

    async def abci_info(self) -> Dict[str, Any]:
        from ..abci import types as abci

        resp = self.node.proxy_app.query.info(abci.RequestInfo())
        return {"response": {
            "data": resp.data, "version": resp.version,
            "app_version": str(resp.app_version),
            "last_block_height": str(resp.last_block_height),
            "last_block_app_hash": b64(resp.last_block_app_hash),
        }}

    async def abci_query(self, path: str = "", data: str = "",
                         height: int = 0, prove: bool = False) -> Dict[str, Any]:
        from ..abci import types as abci

        resp = self.node.proxy_app.query.query(abci.RequestQuery(
            data=bytes.fromhex(data) if data else b"",
            path=path, height=int(height), prove=bool(prove)))
        out = {
            "code": resp.code, "log": resp.log, "info": resp.info,
            "index": str(resp.index), "key": b64(resp.key),
            "value": b64(resp.value), "height": str(resp.height),
            "codespace": resp.codespace,
        }
        if resp.proof_ops:
            out["proofOps"] = {"ops": [
                {"type": op.type, "key": b64(op.key), "data": b64(op.data)}
                for op in resp.proof_ops]}
        return {"response": out}

    # -- mempool / broadcast (rpc/core/mempool.go) ---------------------------

    async def unconfirmed_txs(self, limit: int = 30) -> Dict[str, Any]:
        txs = self.node.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": str(len(txs)),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(sum(len(t) for t in txs)),
            "txs": [b64(t) for t in txs],
        }

    async def num_unconfirmed_txs(self) -> Dict[str, Any]:
        return {
            "n_txs": str(self.node.mempool.size()),
            "total": str(self.node.mempool.size()),
            "total_bytes": "0",
        }

    def _mark_rpc_received(self, raw: bytes) -> bytes:
        """Open the tx's lifecycle record (libs/txlife.py) at the RPC
        front door; returns the tx hash every broadcast variant needs."""
        tx_hash = hashlib.sha256(raw).digest()
        tl = getattr(self.node.mempool, "txlife", None)
        if tl is not None:
            tl.mark(tx_hash, "rpc_received")
        return tx_hash

    async def broadcast_tx_async(self, tx: str) -> Dict[str, Any]:
        raw = _decode_tx_param(tx)
        tx_hash = self._mark_rpc_received(raw)
        ingest = getattr(self.node, "ingest", None)
        if ingest is not None:
            # async contract is fire-and-forget, but a shed is still an
            # explicit (reason-labeled) rejection, not a silent drop
            if not ingest.submit_nowait(raw):
                return {"code": 1, "data": "", "log": "shed",
                        "codespace": "ingest", "hash": hexu(tx_hash)}
        else:
            asyncio.get_running_loop().call_soon(self._check_tx_quiet, raw)
        return {"code": 0, "data": "", "log": "", "codespace": "",
                "hash": hexu(tx_hash)}

    def _check_tx_quiet(self, raw: bytes) -> None:
        """broadcast_tx_async's deferred CheckTx: admission errors (full
        mempool, duplicate) have no response to ride on — swallow them
        instead of dumping a traceback per tx into the loop's exception
        handler under load."""
        from ..mempool.clist_mempool import MempoolError

        try:
            self.node.mempool.check_tx(raw)
        except MempoolError:
            pass

    async def _admit_tx(self, raw: bytes):
        """One admission seam for the sync/commit broadcast variants:
        through the async ingest pipeline when the node carries one
        (bounded intake, reason-labeled sheds, batched pre-verification
        — overload comes back as an explicit non-zero code, never a
        stall or an RPC 500), else the legacy inline CheckTx."""
        ingest = getattr(self.node, "ingest", None)
        if ingest is not None:
            return await ingest.submit(raw)
        return self.node.mempool.check_tx(raw)

    async def broadcast_tx_sync(self, tx: str) -> Dict[str, Any]:
        raw = _decode_tx_param(tx)
        tx_hash = self._mark_rpc_received(raw)
        res = await self._admit_tx(raw)
        return {"code": res.code, "data": b64(res.data), "log": res.log,
                "codespace": getattr(res, "codespace", ""),
                "hash": hexu(tx_hash)}

    async def broadcast_tx_commit(self, tx: str) -> Dict[str, Any]:
        """(rpc/core/mempool.go:64) CheckTx, then wait for the DeliverTx
        event with this tx's hash, bounded by timeout_broadcast_tx_commit."""
        raw = _decode_tx_param(tx)
        tx_hash = self._mark_rpc_received(raw)
        bus = self.node.event_bus
        sub_id = f"rpc-btc-{tx_hash.hex()[:16]}-{time.monotonic_ns()}"
        query = (f"{tme.EVENT_TYPE_KEY}='{tme.EVENT_TX}' AND "
                 f"{tme.TX_HASH_KEY}='{tx_hash.hex().upper()}'")
        sub = bus.subscribe(sub_id, query)
        try:
            check = await self._admit_tx(raw)
            if check.code != 0:
                return {
                    "check_tx": enc_tx_result(check),
                    "deliver_tx": enc_tx_result(_EmptyResult()),
                    "hash": hexu(tx_hash), "height": "0",
                }
            timeout = self.node.config.rpc.timeout_broadcast_tx_commit
            try:
                msg = await asyncio.wait_for(sub.next(), timeout)
            except asyncio.TimeoutError:
                raise RPCError(-32603, "timed out waiting for tx to be included "
                                       "in a block")
            ev = msg.data
            return {
                "check_tx": enc_tx_result(check),
                "deliver_tx": enc_tx_result(ev.result),
                "hash": hexu(tx_hash),
                "height": str(ev.height),
            }
        finally:
            bus.unsubscribe_all(sub_id)

    async def broadcast_evidence(self, evidence: Dict[str, Any]) -> Dict[str, Any]:
        raise RPCError(-32603, "evidence decoding over RPC not supported yet")

    # -- indexer routes (rpc/core/tx.go, blocks.go BlockSearch) --------------

    def _tx_indexer(self):
        idx = self.node.tx_indexer
        if idx is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        return idx

    async def tx(self, hash: str, prove: bool = False) -> Dict[str, Any]:
        r = self._tx_indexer().get(bytes.fromhex(hash))
        if r is None:
            raise RPCError(-32603, f"tx {hash} not found")
        return _enc_tx_search_result(r)

    async def tx_search(self, query: str, prove: bool = False, page: int = 1,
                        per_page: int = 30, order_by: str = "asc"
                        ) -> Dict[str, Any]:
        results = self._tx_indexer().search(query, limit=10000)
        if order_by == "desc":
            results = list(reversed(results))
        page, per_page = max(1, int(page)), min(100, int(per_page))
        start = (page - 1) * per_page
        sel = results[start:start + per_page]
        return {"txs": [_enc_tx_search_result(r) for r in sel],
                "total_count": str(len(results))}

    # -- light-client serving plane (light/serve.py) -------------------------

    def _light_serve(self):
        plane = getattr(self.node, "light_serve", None)
        if plane is None:
            raise RPCError(-32601, "light serving is disabled")
        return plane

    async def light_header(self, height: int = 0, trusted_height: int = 0,
                           client: str = "") -> Dict[str, Any]:
        """Signed header + commit for a light client, served through the
        bisection-aware cache. A declared ``trusted_height`` prefetches and
        pins the bisection-skeleton heights of the span."""
        from ..light.serve import ShedError

        plane = self._light_serve()
        if height:
            height = self._height_or_latest(height)
        try:
            return plane.serve_header(int(height), int(trusted_height),
                                      client_id=str(client))
        except ShedError as e:
            raise RPCError(-32005, str(e), data=e.reason)
        except KeyError as e:
            raise RPCError(-32603, str(e))

    async def light_verify(self, height: int, trusted_height: int,
                           trust_num: int = 1, trust_den: int = 3,
                           client: str = "") -> Dict[str, Any]:
        """Trusting-verify ``height`` against ``trusted_height`` with the
        node's own stores as the source, through the verification
        coalescer: concurrent calls share ONE batched device dispatch and
        get the scalar-spec verdict byte-identically."""
        from ..light.serve import ShedError

        plane = self._light_serve()
        try:
            err = await plane.serve_verify(
                int(height), int(trusted_height),
                trust_level=(int(trust_num), int(trust_den)),
                client_id=str(client))
        except ShedError as e:
            raise RPCError(-32005, str(e), data=e.reason)
        except KeyError as e:
            raise RPCError(-32603, str(e))
        if err is not None:
            raise RPCError(-32010, f"light verification failed: {err}",
                           data=type(err).__name__)
        return {"verified": True, "height": str(int(height)),
                "trusted_height": str(int(trusted_height)),
                "trust_level": f"{int(trust_num)}/{int(trust_den)}"}

    async def lightserve_status(self) -> Dict[str, Any]:
        """Coalescer/cache/limiter counters for the serving plane."""
        return self._light_serve().status()

    async def block_search(self, query: str, page: int = 1, per_page: int = 30,
                           order_by: str = "asc") -> Dict[str, Any]:
        idx = self.node.block_indexer
        if idx is None:
            raise RPCError(-32603, "block indexing is disabled")
        heights = idx.search(query, limit=10000)
        if order_by == "desc":
            heights = list(reversed(heights))
        page, per_page = max(1, int(page)), min(100, int(per_page))
        sel = heights[(page - 1) * per_page:(page - 1) * per_page + per_page]
        blocks = []
        for h in sel:
            blk = self.node.block_store.load_block(h)
            meta = self.node.block_store.load_block_meta(h)
            if blk is not None:
                blocks.append({"block_id": enc_block_id(meta.block_id),
                               "block": enc_block(blk)})
        return {"blocks": blocks, "total_count": str(len(heights))}


class _EmptyResult:
    code = 0
    data = b""
    log = ""
    info = ""
    gas_wanted = 0
    gas_used = 0
    codespace = ""


def _decode_tx_param(tx: str) -> bytes:
    """Accept base64 (JSON-RPC convention) or 0x-hex."""
    import base64 as _b64

    if isinstance(tx, bytes):
        return tx
    if tx.startswith("0x"):
        return bytes.fromhex(tx[2:])
    return _b64.b64decode(tx)


# the route table (routes.go:10-49); name -> handler attribute
ROUTES = [
    "health", "status", "net_info", "genesis", "genesis_chunked",
    "blockchain", "block", "block_by_hash", "block_results", "commit",
    "check_tx", "validators", "consensus_state", "dump_consensus_state",
    "consensus_stage_timeline", "tx_timeline", "consensus_params",
    "abci_info", "abci_query",
    "unconfirmed_txs", "num_unconfirmed_txs", "broadcast_tx_async",
    "broadcast_tx_sync", "broadcast_tx_commit", "broadcast_evidence",
    "tx", "tx_search", "block_search",
    "light_header", "light_verify", "lightserve_status",
]

# served only when config.rpc.unsafe is set (routes.go:52 AddUnsafeRoutes)
UNSAFE_ROUTES = ["dial_seeds", "dial_peers", "unsafe_flush_mempool"]


def _enc_tx_search_result(r) -> Dict[str, Any]:
    import hashlib as _h

    return {
        "hash": hexu(_h.sha256(r.tx).digest()),
        "height": str(r.height),
        "index": r.index,
        "tx_result": {
            "code": r.code, "data": b64(r.data), "log": r.log,
            "gas_wanted": str(r.gas_wanted), "gas_used": str(r.gas_used),
            "events": r.events,
        },
        "tx": b64(r.tx),
    }
