"""RPC clients (reference rpc/client/):

* :class:`HTTPClient` — remote JSON-RPC over HTTP + WS subscriptions
  (rpc/jsonrpc/client/http_json_client.go, ws_client.go);
* :class:`LocalClient` — direct in-proc calls against a node's Environment
  (rpc/client/local — used by tests and the light-client provider).

Both expose the same ``await client.call("block", height=5)`` surface plus
typed convenience wrappers for the routes the framework itself consumes.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import Any, AsyncIterator, Dict, Optional

import aiohttp

from .core import Environment, RPCError


class HTTPClient:
    def __init__(self, base_url: str):
        # accept tcp://host:port or http://host:port
        if base_url.startswith("tcp://"):
            base_url = "http://" + base_url[len("tcp://"):]
        self.base_url = base_url.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self._ids = itertools.count(1)

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def call(self, method: str, **params) -> Any:
        session = await self._ensure()
        payload = {"jsonrpc": "2.0", "id": next(self._ids),
                   "method": method, "params": params}
        async with session.post(self.base_url + "/", json=payload) as resp:
            doc = await resp.json()
        if doc.get("error"):
            e = doc["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""),
                           e.get("data", ""))
        return doc["result"]

    async def subscribe(self, query: str) -> AsyncIterator[Dict[str, Any]]:
        """Async iterator of events from the /websocket endpoint."""
        session = await self._ensure()
        ws = await session.ws_connect(self.base_url + "/websocket")
        await ws.send_json({"jsonrpc": "2.0", "id": 1, "method": "subscribe",
                            "params": {"query": query}})
        first = json.loads((await ws.receive()).data)  # subscribe ack
        if first.get("error"):
            raise RPCError(-1, str(first["error"]))

        async def gen():
            try:
                async for msg in ws:
                    doc = json.loads(msg.data)
                    if doc.get("result"):
                        yield doc["result"]
            finally:
                await ws.close()
        return gen()

    # typed helpers ----------------------------------------------------------

    async def status(self) -> Dict[str, Any]:
        return await self.call("status")

    async def block(self, height: Optional[int] = None) -> Dict[str, Any]:
        return await self.call("block", **({"height": height} if height else {}))

    async def commit(self, height: Optional[int] = None) -> Dict[str, Any]:
        return await self.call("commit", **({"height": height} if height else {}))

    async def validators(self, height: Optional[int] = None, page: int = 1,
                         per_page: int = 100) -> Dict[str, Any]:
        params = {"page": page, "per_page": per_page}
        if height:
            params["height"] = height
        return await self.call("validators", **params)

    async def broadcast_tx_commit(self, tx: bytes) -> Dict[str, Any]:
        import base64
        return await self.call("broadcast_tx_commit",
                               tx=base64.b64encode(tx).decode())

    async def abci_query(self, path: str, data: bytes, height: int = 0,
                         prove: bool = False) -> Dict[str, Any]:
        return await self.call("abci_query", path=path, data=data.hex(),
                               height=height, prove=prove)


class LocalClient:
    """In-proc client: same interface, zero sockets (rpc/client/local)."""

    def __init__(self, node):
        self.env = Environment(node)
        self.node = node

    async def call(self, method: str, **params) -> Any:
        handler = getattr(self.env, method, None)
        if handler is None:
            raise RPCError(-32601, f"method {method!r} not found")
        return await handler(**params)

    async def status(self):
        return await self.call("status")

    async def block(self, height=None):
        return await self.call("block", **({"height": height} if height else {}))

    async def commit(self, height=None):
        return await self.call("commit", **({"height": height} if height else {}))

    async def validators(self, height=None, page=1, per_page=100):
        params = {"page": page, "per_page": per_page}
        if height:
            params["height"] = height
        return await self.call("validators", **params)

    async def broadcast_tx_commit(self, tx: bytes):
        import base64
        return await self.call("broadcast_tx_commit",
                               tx=base64.b64encode(tx).decode())
