"""Operator CLI (reference cmd/tendermint/main.go:16-49 command set).

Usage:  python -m tendermint_tpu.cmd [--home DIR] <command> [...]

Commands: init, start, testnet, gen-node-key, show-node-id, gen-validator,
show-validator, reset-unsafe, version. (replay/rollback/light arrive with
their subsystems.)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import shutil
import sys
import time

from . import config as cfgmod
from .config import Config

VERSION = "tendermint-tpu/0.1.0"


def cmd_init(args) -> int:
    """(cmd/tendermint/commands/init.go) scaffold config + genesis + keys."""
    from .p2p import NodeKey
    from .privval.file_pv import FilePV
    from .types import GenesisDoc, GenesisValidator

    cfg = Config(root_dir=args.home)
    if args.chain_id:
        cfg.base.chain_id = args.chain_id
    os.makedirs(os.path.join(args.home, cfgmod.CONFIG_DIR), exist_ok=True)
    os.makedirs(os.path.join(args.home, cfgmod.DATA_DIR), exist_ok=True)

    pv_key, pv_state = cfg.priv_validator_key_file(), cfg.priv_validator_state_file()
    if os.path.exists(pv_key):
        pv = FilePV.load(pv_key, pv_state)
        print(f"found existing validator key {pv_key}")
    else:
        pv = FilePV.generate(pv_key, pv_state)
        pv.save()
        print(f"generated validator key {pv_key}")

    nk = NodeKey.load_or_gen(cfg.node_key_file())
    print(f"node id: {nk.id}")

    gen_file = cfg.genesis_file()
    if not os.path.exists(gen_file):
        chain_id = args.chain_id or f"test-chain-{os.urandom(3).hex()}"
        genesis = GenesisDoc(
            chain_id=chain_id,
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        )
        genesis.save_as(gen_file)
        print(f"generated genesis {gen_file} (chain {chain_id})")
    cfg.save()
    print(f"wrote config {os.path.join(args.home, 'config', 'config.toml')}")
    return 0


def cmd_start(args) -> int:
    """(cmd/tendermint/commands/run_node.go) run a node until SIGINT."""
    from .node import Node

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname).1s %(message)s")
    # persistent XLA compile cache: the batched-verify kernels take minutes
    # to compile cold; without this every fresh node process pays that on
    # its first device-routed batch (TMTPU_JAX_CACHE overrides, e.g. the
    # e2e runner points all subprocess nodes at one shared cache). The
    # helper also fingerprints the cache dir and warns LOUDLY when it was
    # built on a host with different CPU features — the cpu_aot_loader
    # SIGILL risk otherwise buried in stderr (MULTICHIP_r05.json).
    try:
        from .libs.compilecache import enable_compile_cache

        cache = os.environ.get("TMTPU_JAX_CACHE") or os.path.join(
            args.home, ".jax_cache")
        warn = enable_compile_cache(cache)
        if warn:
            logging.getLogger("tmtpu.node").warning("%s", warn)
    except Exception:
        pass
    cfg = Config.load(args.home)
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    cfg.validate_basic()
    node = Node.default(cfg)

    # TMTPU_TRACE_OUT=<prefix>: run the whole node under the span tracer and
    # write <prefix>-<pid>.json (Chrome trace-event JSON) on shutdown, so a
    # localnet's per-height live-plane breakdown (gossip wait / WAL sync /
    # apply) is recoverable with tools/trace_summary.py --by-height
    trace_prefix = os.environ.get("TMTPU_TRACE_OUT")
    from .libs.trace import tracer as _tracer

    # stamp the trace with this node's identity + wall↔perf epoch so
    # tools/trace_merge.py can align N nodes' traces onto one timeline
    # (TMTPU_NODE_ID overrides for runners that name nodes themselves)
    _tracer.set_identity(os.environ.get("TMTPU_NODE_ID")
                         or cfg.base.moniker or f"pid-{os.getpid()}")
    if trace_prefix:
        _tracer.enable()

    async def run():
        # SIGUSR1 -> synchronous in-process dump of thread stacks, asyncio
        # task stacks, round state and peer table — works even when the
        # event loop is wedged (reference keeps a pprof listener for this,
        # node/node.go:896; see libs/debugdump.py)
        from .libs import debugdump

        debugdump.install(args.home, node=node,
                          loop=asyncio.get_running_loop())
        await node.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        try:
            import signal

            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
        fatal = asyncio.create_task(node.fatal_event.wait())
        stopped = asyncio.create_task(stop.wait())
        await asyncio.wait({fatal, stopped},
                           return_when=asyncio.FIRST_COMPLETED)
        if node.fatal_event.is_set():
            print(f"FATAL: {node.fatal_error}")
            await node.stop()
            raise SystemExit(1)
        print("shutting down...")
        fatal.cancel()
        await node.stop()
        if trace_prefix:
            from .libs.trace import tracer as _tracer

            path = f"{trace_prefix}-{os.getpid()}.json"
            _tracer.write(path)
            print(f"wrote span trace {path}")

    asyncio.run(run())
    return 0


def cmd_testnet(args) -> int:
    """(cmd/tendermint/commands/testnet.go) N-node config bundles with a
    shared genesis and fully-meshed persistent peers."""
    from .p2p import NodeKey
    from .privval.file_pv import FilePV
    from .types import GenesisDoc, GenesisValidator

    n = args.v
    out = args.output_dir
    chain_id = args.chain_id or f"chain-{os.urandom(3).hex()}"
    pvs, node_keys, configs = [], [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = Config(root_dir=home)
        cfg.base.chain_id = chain_id
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i + 1}"
        if getattr(args, "prometheus", False):
            # metrics ports start right after the nodes' p2p/rpc block
            # ([starting_port, starting_port + 2v)), collision-free for any v
            cfg.instrumentation.prometheus = True
            cfg.instrumentation.prometheus_listen_addr = (
                f"tcp://127.0.0.1:{args.starting_port + 2 * args.v + i}")
        os.makedirs(os.path.join(home, cfgmod.CONFIG_DIR), exist_ok=True)
        os.makedirs(os.path.join(home, cfgmod.DATA_DIR), exist_ok=True)
        pv = FilePV.generate(cfg.priv_validator_key_file(),
                             cfg.priv_validator_state_file())
        pv.save()
        nk = NodeKey.load_or_gen(cfg.node_key_file())
        pvs.append(pv)
        node_keys.append(nk)
        configs.append(cfg)

    genesis = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=time.time_ns(),
        validators=[GenesisValidator(pv.get_pub_key(), 10) for pv in pvs],
    )
    for i, cfg in enumerate(configs):
        peers = ",".join(
            f"{node_keys[j].id}@127.0.0.1:{args.starting_port + 2 * j}"
            for j in range(n) if j != i)
        cfg.p2p.persistent_peers = peers
        cfg.base.fast_sync = False
        genesis.save_as(cfg.genesis_file())
        cfg.save()
    print(f"wrote {n}-node testnet under {out} (chain {chain_id})")
    for i, nk in enumerate(node_keys):
        print(f"  node{i}: id={nk.id} p2p={configs[i].p2p.laddr} "
              f"rpc={configs[i].rpc.laddr}")
    return 0


def cmd_gen_node_key(args) -> int:
    from .p2p import NodeKey

    cfg = Config(root_dir=args.home)
    nk = NodeKey.load_or_gen(cfg.node_key_file())
    print(nk.id)
    return 0


def cmd_show_node_id(args) -> int:
    from .p2p import NodeKey

    cfg = Config(root_dir=args.home)
    nk = NodeKey.load(cfg.node_key_file())
    print(nk.id)
    return 0


def cmd_gen_validator(args) -> int:
    from .privval.file_pv import FilePV

    pv = FilePV.generate("", "")
    pub = pv.get_pub_key()
    print(json.dumps({
        "address": pub.address().hex().upper(),
        "pub_key": {"type": "tendermint/PubKeyEd25519",
                    "value": pub.bytes().hex()},
        "priv_key": {"type": "tendermint/PrivKeyEd25519",
                     "value": pv.priv_key.bytes().hex()},
    }, indent=2))
    return 0


def cmd_show_validator(args) -> int:
    from .privval.file_pv import FilePV

    cfg = Config(root_dir=args.home)
    pv = FilePV.load(cfg.priv_validator_key_file(),
                     cfg.priv_validator_state_file())
    pub = pv.get_pub_key()
    print(json.dumps({"type": "tendermint/PubKeyEd25519",
                      "value": pub.bytes().hex()}))
    return 0


def cmd_reset_unsafe(args) -> int:
    """(cmd unsafe-reset-all) wipe data, keep config + validator key."""
    cfg = Config(root_dir=args.home)
    data = os.path.join(args.home, cfgmod.DATA_DIR)
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    # reset priv validator state (sign state) but keep the key
    state_file = cfg.priv_validator_state_file()
    with open(state_file, "w") as f:
        json.dump({"height": 0, "round": 0, "step": 0}, f)
    print(f"reset {data}")
    return 0


def cmd_rollback(args) -> int:
    """(cmd rollback; state/rollback.go) roll state back one height."""
    from .node import _make_db
    from .state.rollback import rollback_state
    from .state.store import StateStore
    from .store import BlockStore

    cfg = Config.load(args.home)
    block_store = BlockStore(_make_db(cfg.base.db_backend, cfg.db_dir(),
                                      "blockstore"))
    state_store = StateStore(_make_db(cfg.base.db_backend, cfg.db_dir(),
                                      "state"))
    height, app_hash = rollback_state(block_store, state_store)
    print(f"rolled back state to height {height} and hash {app_hash.hex()}")
    return 0


def cmd_light(args) -> int:
    """(cmd/tendermint/commands/light.go) verifying light proxy."""
    from .light.client import LightClient, TrustOptions
    from .light.provider import HTTPProvider
    from .light.proxy import LightProxy
    from .rpc.client import HTTPClient

    async def run():
        primary = HTTPClient(args.primary)
        provider = HTTPProvider(args.chain_id, primary)
        witnesses = [HTTPProvider(args.chain_id, HTTPClient(w))
                     for w in (args.witnesses.split(",") if args.witnesses
                               else [])]
        lc = LightClient(
            args.chain_id,
            TrustOptions(args.trust_period, args.trust_height,
                         bytes.fromhex(args.trust_hash)),
            provider, witnesses)
        from .node import _parse_laddr

        proxy = LightProxy(lc, primary)
        host, port = _parse_laddr(args.laddr)
        bound = await proxy.start(host, port)
        print(f"light proxy for {args.chain_id} on port {bound} "
              f"(primary {args.primary})")
        stop = asyncio.Event()
        try:
            import signal

            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
        await stop.wait()
        await proxy.stop()

    asyncio.run(run())
    return 0


def cmd_version(args) -> int:
    print(VERSION)
    return 0


def _fetch_rpc(base_url: str, path: str):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(f"{base_url}/{path}", timeout=10) as r:
        return _json.load(r)


def cmd_debug(args) -> int:
    """(cmd/tendermint/commands/debug/{dump,kill}.go) capture a diagnostic
    bundle from a RUNNING node over RPC + its home dir: status, net_info,
    dump_consensus_state, consensus_state, config, WAL tail. ``debug kill``
    captures the bundle and then SIGKILLs the node."""
    import shutil
    import signal as _signal
    import time as _time

    cfg = Config.load(args.home)
    rpc = args.rpc_laddr or cfg.rpc.laddr
    base = "http://" + rpc.split("://", 1)[-1]
    out = args.output_dir or os.path.join(
        args.home, f"debug-{int(_time.time())}")
    os.makedirs(out, exist_ok=True)

    for route in ("status", "net_info", "consensus_state",
                  "dump_consensus_state"):
        try:
            doc = _fetch_rpc(base, route)
            with open(os.path.join(out, f"{route}.json"), "w") as f:
                json.dump(doc, f, indent=2)
        except Exception as e:
            with open(os.path.join(out, f"{route}.err"), "w") as f:
                f.write(str(e))

    # config + WAL tail from the home dir
    cfg_file = os.path.join(args.home, cfgmod.CONFIG_DIR, "config.toml")
    if os.path.exists(cfg_file):
        shutil.copy(cfg_file, os.path.join(out, "config.toml"))
    try:
        from .consensus.wal import WAL

        # repair=False: the node may be live and holding the file open for
        # append — a read-only observer must never truncate its tail
        wal = WAL(cfg.wal_file(), repair=False)
        msgs = list(wal.iter_messages())[-200:]
        with open(os.path.join(out, "wal_tail.jsonl"), "w") as f:
            for m in msgs:
                f.write(json.dumps({"type": m.type, "time_ns": m.time_ns,
                                    "data": m.data}, default=str) + "\n")
    except Exception as e:
        with open(os.path.join(out, "wal_tail.err"), "w") as f:
            f.write(str(e))

    print(f"wrote debug bundle to {out}")
    if args.action == "kill":
        pid = args.pid
        if not pid:
            print("debug kill: --pid required", file=sys.stderr)
            return 1
        # in-process dump first (debug/kill.go captures goroutine profiles
        # before the kill): the node's SIGUSR1 handler writes stacks to its
        # home even when its loop — and therefore RPC — is wedged
        try:
            os.kill(pid, _signal.SIGUSR1)
            _time.sleep(1.0)
            os.kill(pid, _signal.SIGKILL)
            print(f"killed pid {pid}")
        except ProcessLookupError:
            print(f"pid {pid} already gone")
    return 0


def cmd_replay(args) -> int:
    """(cmd/tendermint/commands/replay.go, consensus/replay_file.go) rebuild
    the node from its home dir — the ABCI handshake replays stored blocks
    into the app (consensus/replay.go ReplayBlocks) — then feed the WAL tail
    for the in-flight height through the real consensus state machine,
    printing each message; ``--console`` pauses between messages."""
    from .consensus.replay import _replay_message
    from .node import Node

    logging.basicConfig(level=logging.WARNING)
    cfg = Config.load(args.home)
    cfg.p2p.laddr = ""      # replay is offline: no listeners
    cfg.rpc.laddr = ""
    node = Node.default(cfg)  # handshake replay of stored blocks happens here
    cs = node.consensus_state
    height = cs.rs.height
    print(f"handshake replayed chain to height {height - 1}; "
          f"replaying WAL for in-flight height {height}")
    count = 0
    cs._replay_mode = True
    try:
        for m in cs.wal.messages_after_end_height(height - 1):
            count += 1
            summary = {k: v for k, v in (m.data or {}).items()
                       if k in ("height", "round", "step", "type",
                                "duration_ns")}
            print(f"#{count:<5} {m.type:<12} {summary}")
            if args.console:
                try:
                    if input("replay> ").strip() in ("q", "quit"):
                        break
                except EOFError:
                    break
            try:
                _replay_message(cs, m)
            except Exception as e:
                print(f"  !! replay error: {e}")
    finally:
        cs._replay_mode = False
    rs = cs.rs
    print(f"replayed {count} WAL messages; round state now "
          f"{rs.height}/{rs.round}/{int(rs.step)}")
    return 0


def cmd_compact_db(args) -> int:
    """(cmd compact-db; reference compacts goleveldb) VACUUM every sqlite
    store under the data dir."""
    import sqlite3

    cfg = Config.load(args.home)
    n = 0
    for name in sorted(os.listdir(cfg.db_dir())):
        if not name.endswith(".db"):
            continue
        path = os.path.join(cfg.db_dir(), name)
        before = os.path.getsize(path)
        con = sqlite3.connect(path)
        con.execute("VACUUM")
        con.close()
        after = os.path.getsize(path)
        print(f"{name}: {before} -> {after} bytes")
        n += 1
    if n == 0:
        print("no .db files found (mem backend?)")
    return 0


def cmd_reindex_event(args) -> int:
    """(cmd reindex-event) rebuild the tx index from stored blocks + their
    persisted ABCI responses (state/txindex kv sink)."""
    from .libs.db import SQLiteDB
    from .state.store import StateStore
    from .state.txindex import KVTxIndexer, TxResult
    from .store import BlockStore

    cfg = Config.load(args.home)
    dbdir = cfg.db_dir()
    block_store = BlockStore(SQLiteDB(os.path.join(dbdir, "blockstore.db")))
    state_store = StateStore(SQLiteDB(os.path.join(dbdir, "state.db")))
    indexer = SQLiteDB(os.path.join(dbdir, "txindex.db"))
    txi = KVTxIndexer(indexer)
    count = 0
    for h in range(block_store.base(), block_store.height() + 1):
        block = block_store.load_block(h)
        resps = state_store.load_abci_responses(h)
        if block is None or resps is None:
            continue
        for i, tx in enumerate(block.data.txs):
            r = resps.deliver_txs[i] if i < len(resps.deliver_txs) else None
            txi.index(TxResult(
                height=h, index=i, tx=tx,
                code=getattr(r, "code", 0), data=getattr(r, "data", b""),
                log=getattr(r, "log", ""),
                gas_wanted=getattr(r, "gas_wanted", 0),
                gas_used=getattr(r, "gas_used", 0),
                events={}))
            count += 1
    print(f"reindexed {count} txs over heights "
          f"{block_store.base()}..{block_store.height()}")
    return 0


def cmd_signer(args) -> int:
    """Remote signer process: serves a FilePV to a node over the privval
    SecretConnection link (the tmkms role; reference privval/signer_server.go).
    Runs until SIGINT."""
    import signal as _signal
    import threading

    from .privval.file_pv import FilePV
    from .privval.signer import SignerServer

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname).1s %(message)s")
    pv = FilePV.load(args.key_file, args.state_file)
    host, _, port = args.addr.rpartition("://")[-1].rpartition(":")
    server = SignerServer(pv, args.chain_id, (host or "127.0.0.1", int(port)))
    server.start()
    stop = threading.Event()
    for sig in (_signal.SIGINT, _signal.SIGTERM):
        _signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tmtpu",
                                description="tendermint-tpu node CLI")
    p.add_argument("--home", default=os.path.expanduser("~/.tmtpu"))
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="scaffold config/genesis/keys")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run a node")
    sp.add_argument("--p2p-laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp.add_argument("--persistent-peers", dest="persistent_peers", default="")
    sp.add_argument("--proxy-app", dest="proxy_app", default="")
    sp.add_argument("--log-level", dest="log_level", default="info")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate N-node localnet configs")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output-dir", dest="output_dir", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", dest="starting_port", type=int,
                    default=26656)
    sp.add_argument("--prometheus", action="store_true",
                    help="serve /metrics on starting_port+2v+i per node")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("light", help="verifying light-client proxy")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True)
    sp.add_argument("--witnesses", default="")
    sp.add_argument("--trust-height", dest="trust_height", type=int,
                    required=True)
    sp.add_argument("--trust-hash", dest="trust_hash", required=True)
    sp.add_argument("--trust-period", dest="trust_period", type=float,
                    default=168 * 3600.0)
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("debug", help="capture a diagnostic bundle "
                                      "(dump) or capture-then-kill")
    sp.add_argument("action", choices=("dump", "kill"))
    sp.add_argument("--output-dir", dest="output_dir", default="")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp.add_argument("--pid", type=int, default=0,
                    help="node pid (required for kill)")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("replay", help="replay blocks + WAL through the "
                                       "state machine (offline)")
    sp.set_defaults(fn=cmd_replay, console=False)

    sp = sub.add_parser("replay-console",
                        help="interactive step-by-step WAL replay")
    sp.set_defaults(fn=cmd_replay, console=True)

    sp = sub.add_parser("signer", help="remote privval signer process")
    sp.add_argument("--key-file", dest="key_file", required=True)
    sp.add_argument("--state-file", dest="state_file", required=True)
    sp.add_argument("--chain-id", dest="chain_id", required=True)
    sp.add_argument("--addr", required=True,
                    help="node's priv_validator_laddr to dial, host:port")
    sp.set_defaults(fn=cmd_signer)

    for name, fn in [("compact-db", cmd_compact_db),
                     ("reindex-event", cmd_reindex_event),
                     ("rollback", cmd_rollback),
                     ("gen-node-key", cmd_gen_node_key),
                     ("show-node-id", cmd_show_node_id),
                     ("gen-validator", cmd_gen_validator),
                     ("show-validator", cmd_show_validator),
                     ("unsafe-reset-all", cmd_reset_unsafe),
                     ("version", cmd_version)]:
        sp = sub.add_parser(name)
        sp.set_defaults(fn=fn)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
