"""Block sync ("fast sync") — download committed blocks from peers and replay
them with windowed, batched commit verification (reference blockchain/v0/,
SURVEY.md §2.7).
"""

from .pool import BlockPool  # noqa: F401
from .reactor import BlockchainReactor  # noqa: F401
