"""Block-sync ("fast sync") reactor — channel 0x40
(reference blockchain/v0/reactor.go:51; pool routine at :255).

TPU-first difference from the reference: the reference verifies ONE commit per
pool-routine iteration (VerifyCommitLight of block N against N+1's
LastCommit, one scalar ed25519 verify per signature). Here a contiguous
window of downloaded blocks is verified as ONE device batch
(types.validator_set.verify_commit_light_batched) whenever the window shares
a validator set (header.validators_hash equality — the hash commits to the
full set), which is the common case; heights where the set changes fall back
to per-block verification. This is baseline config #5 (10k-block replay at
1000 validators).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Tuple

from ..p2p import BLOCKCHAIN_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from ..state import BlockExecutor
from ..state.state import State
from ..store import BlockStore
from ..types.basic import BlockID
from ..types.block import Block
from ..crypto.batch import BatchVerifier, precomputed_verdicts
from ..types.validator_set import verify_commit_light_batched
from .msgs import (
    BlockRequest,
    BlockResponse,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_msg,
    encode_msg,
)
from .pool import BlockPool

logger = logging.getLogger("tmtpu.blockchain")


class FatalSyncError(Exception):
    """A deterministic local fault during block application: the reference
    panics here (v0/reactor.go ApplyBlock err); we stop the sync loop and
    propagate so the node halts and restart replay reconciles."""


# verify/apply at most this many blocks per batch; bounds device batch size
# (10k validators x 64 blocks = 640k sigs would exceed one comfortable batch)
VERIFY_WINDOW = 16
# window precompute engages at/above this many candidate signatures (both
# planes); below it the per-block path is cheaper and compile-free
PRECOMPUTE_MIN_SIGS = 2048
POLL_INTERVAL = 0.01
STATUS_UPDATE_INTERVAL = 10.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0


class BlockchainReactor(Reactor):
    def __init__(self, state: State, block_exec: BlockExecutor,
                 block_store: BlockStore, fast_sync: bool,
                 consensus_reactor=None, on_fatal=None):
        super().__init__("BLOCKCHAIN")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.pool = BlockPool(max(self.store.height(), state.last_block_height) + 1)
        self._pool_task: Optional[asyncio.Task] = None
        # called with the exception on a fatal (deterministic) sync fault;
        # the node wires this to shut itself down (the reference panics)
        self.on_fatal = on_fatal
        self.synced = asyncio.Event()  # set on switch-to-consensus
        self.blocks_synced = 0

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5,
                                  send_queue_capacity=1000,
                                  recv_message_capacity=10 * 1024 * 1024)]

    async def start(self) -> None:
        # idempotent: Switch.start() starts every registered reactor, and the
        # node/state-sync paths may call start again — two concurrent pool
        # routines would double-apply blocks
        if self.fast_sync:
            if self._pool_task is None:
                self._pool_task = asyncio.create_task(self._pool_routine())
                self._pool_task.add_done_callback(self._pool_done)
        else:
            self.synced.set()

    async def switch_to_fast_sync(self, state: State) -> None:
        """(reactor.go SwitchToFastSync) enter fast sync from a state-synced
        state: re-seed the pool at the bootstrapped height and start."""
        self.state = state
        self.fast_sync = True
        self.synced.clear()
        self.pool = BlockPool(state.last_block_height + 1)
        if self._pool_task is None:
            self._pool_task = asyncio.create_task(self._pool_routine())
            self._pool_task.add_done_callback(self._pool_done)

    def _pool_done(self, task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.critical("block sync died: %s", exc)
            if self.on_fatal is not None:
                self.on_fatal(exc)

    async def stop(self) -> None:
        if self._pool_task is not None:
            self._pool_task.cancel()
            self._pool_task = None

    # -- peer lifecycle -----------------------------------------------------

    async def add_peer(self, peer: Peer) -> None:
        # advertise our range so the peer can sync from us (reactor.go AddPeer)
        peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(
            StatusResponse(self.store.height(), self.store.base())))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self.pool.remove_peer(peer.id)

    # -- inbound ------------------------------------------------------------

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = decode_msg(msg_bytes)
        if isinstance(msg, BlockRequest):
            block = self.store.load_block(msg.height)
            if block is not None:
                peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(BlockResponse(block)))
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(NoBlockResponse(msg.height)))
        elif isinstance(msg, StatusRequest):
            peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(
                StatusResponse(self.store.height(), self.store.base())))
        elif isinstance(msg, StatusResponse):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, BlockResponse):
            status = self.pool.add_block(peer.id, msg.block)
            if status == "unsolicited":
                # never requested from anyone: peer error, not a free
                # bandwidth vector (reference reactor treats it as such).
                # "stale" (timed-out/reassigned request arriving late) is an
                # honest slow peer and is silently dropped.
                logger.warning("unsolicited block h=%d from %s",
                               msg.block.header.height, peer.id)
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(
                        peer, f"unsolicited block at {msg.block.header.height}")
        elif isinstance(msg, NoBlockResponse):
            self.pool.no_block(peer.id, msg.height)

    # -- the sync loop (reactor.go:255 poolRoutine) --------------------------

    async def _pool_routine(self) -> None:
        last_status = 0.0
        last_switch_check = 0.0
        self.switch and self._broadcast_status_request()
        while True:
            try:
                now = time.monotonic()
                if now - last_status > STATUS_UPDATE_INTERVAL:
                    self._broadcast_status_request()
                    last_status = now
                for peer_id, height in self.pool.schedule_requests():
                    peer = self.switch.peers.get(peer_id) if self.switch else None
                    if peer is not None:
                        peer.try_send(BLOCKCHAIN_CHANNEL,
                                      encode_msg(BlockRequest(height)))
                await self._process_window()
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    if self.pool.is_caught_up():
                        logger.info("fast sync complete at height %d (%d blocks)",
                                    self.state.last_block_height, self.blocks_synced)
                        self._switch_to_consensus()
                        return
                await asyncio.sleep(POLL_INTERVAL)
            except asyncio.CancelledError:
                raise
            except FatalSyncError:
                logger.critical("fatal block-sync error; halting sync loop")
                raise
            except Exception:
                logger.exception("pool routine error")
                await asyncio.sleep(0.1)

    def _broadcast_status_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(BLOCKCHAIN_CHANNEL, encode_msg(StatusRequest()))

    def _switch_to_consensus(self) -> None:
        self.synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(self.state)

    async def _process_window(self) -> None:
        """Verify+apply a contiguous run of downloaded blocks.

        Block N's canonical commit is block N+1's LastCommit, so a run of
        k+1 blocks yields k verifiable (block, commit) pairs. All pairs whose
        headers commit to the CURRENT validator set are verified as one
        device batch; the rest of the run waits for the state to advance.
        """
        window = self.pool.peek_window(VERIFY_WINDOW + 1)
        if len(window) < 2:
            return
        cur_vals_hash = self.state.validators.hash()
        pairs: List[Tuple[Block, str, Block, str]] = []  # (blk, peer, next, npeer)
        for (blk, peer_id), (nxt, npeer_id) in zip(window, window[1:]):
            if blk.header.validators_hash != cur_vals_hash:
                break  # validator set changes mid-window: verify after advance
            pairs.append((blk, peer_id, nxt, npeer_id))
        if not pairs:
            # the very next block claims a different valset: its commit can't
            # be checked against our state -> bad block (validate_block would
            # reject it anyway); redo from this height.
            first, first_peer = window[0]
            await self._punish(self.pool.redo(first.header.height),
                               "block valset hash mismatch")
            return

        entries = []
        for blk, _p, nxt, _np in pairs:
            parts_header = blk.make_part_set().header()
            block_id = BlockID(blk.hash(), parts_header)
            entries.append((self.state.validators, self.state.chain_id,
                            block_id, blk.header.height, nxt.last_commit))

        # Pre-verify the window's OTHER signature plane in the same scope:
        # apply_block -> validate_block re-checks each block's LastCommit
        # with the full VerifyCommit predicate (state/validation.py:55,
        # reference state/validation.go:72). Verified one commit at a time
        # that is a full-dispatch-latency device call per block; batched
        # here, the apply loop's verify_commit hits precomputed verdicts and
        # the whole window costs one device round-trip for BOTH planes.
        # off-loop: a cold backend compile or a big host batch inside the
        # loop would stall RPC/p2p liveness for the whole node
        pre = await asyncio.get_running_loop().run_in_executor(
            None, self._precompute_last_commit_verdicts, pairs)
        token = precomputed_verdicts.set(pre) if pre is not None else None
        try:
            results = verify_commit_light_batched(entries)
            await self._apply_window(pairs, results, entries)
        finally:
            if token is not None:
                precomputed_verdicts.reset(token)

    def _precompute_last_commit_verdicts(self, pairs) -> "Optional[dict]":
        """(pk, sign_bytes, sig) -> verdict for every candidate signature the
        window will verify — the light entries above AND each block's
        LastCommit full-commit candidates. Returns None when the window's
        LastCommits span a validator-set change (the per-block fallback is
        correct there; _process_window already bounds pairs to one set for
        the light plane)."""
        try:
            return self._precompute_inner(pairs)
        except Exception as e:
            # peer data is untrusted here (nothing has validated these
            # blocks yet): ANY malformed shape — last_commit=None, odd sig
            # sizes — falls back to the per-block path, whose per-entry
            # error handling turns bad blocks into pool.redo + punish
            # instead of wedging the pool routine
            logger.debug("window precompute skipped: %s", e)
            return None

    def _precompute_inner(self, pairs) -> "Optional[dict]":
        first_h = pairs[0][0].header.height
        # small-net windows (few validators or a short tail) stay on the
        # per-block path: doubling a tiny batch buys nothing and must not
        # push it over the device-routing threshold (a cold XLA compile in a
        # fresh node process would dwarf the verification itself)
        n_sigs = sum(len(blk.last_commit.signatures) if blk.last_commit else 0
                     for blk, _p, _n, _np in pairs) * 2
        if n_sigs < PRECOMPUTE_MIN_SIGS:
            return None
        bv = BatchVerifier()
        keys: List[Tuple[bytes, bytes, bytes]] = []

        def _add(pub, msg, sig):
            bv.add(pub, msg, sig)
            keys.append((pub.bytes(), msg, sig))

        for blk, _p, nxt, _np in pairs:
            # block h's LastCommit was signed by the valset of h-1: the first
            # window block checks against state.last_validators, later ones
            # against the (stable) current set
            vals = (self.state.last_validators if blk.header.height == first_h
                    else self.state.validators)
            lc = blk.last_commit
            if lc is not None and len(lc.signatures):
                if len(lc.signatures) != vals.size():
                    return None  # shape mismatch: let validate_block decide
                sb = lc.vote_sign_bytes_all(self.state.chain_id)
                for idx, cs in enumerate(lc.signatures):
                    if not cs.absent():
                        _add(vals.validators[idx].pub_key, sb[idx],
                             cs.signature)
            # the light plane of THIS window (nxt.last_commit rows) shares
            # the batch: one device call covers both planes. Candidate rule
            # MUST mirror verify_commit_light_batched (validator_set.py):
            # for_block sigs keyed by (pk, vote_sign_bytes_all row, sig) —
            # any divergence makes BatchVerifier miss the precomputed dict
            # and silently re-dispatch, not mis-verify (all-or-nothing hit)
            cur = self.state.validators
            sbn = nxt.last_commit.vote_sign_bytes_all(self.state.chain_id)
            for idx, cs in enumerate(nxt.last_commit.signatures):
                if cs.for_block() and idx < cur.size():
                    _add(cur.validators[idx].pub_key, sbn[idx], cs.signature)
        if not keys:
            return None
        _, verdicts = bv.verify()
        return {t: bool(v) for t, v in zip(keys, verdicts)}

    async def _apply_window(self, pairs, results, entries) -> None:
        for (blk, peer_id, nxt, npeer_id), err, entry in zip(
                pairs, results, entries):
            if err is not None:
                logger.warning("invalid block/commit at height %d: %s",
                               blk.header.height, err)
                bad = self.pool.redo(blk.header.height)
                bad.update({peer_id, npeer_id})
                await self._punish(bad, f"bad block at {blk.header.height}: {err}")
                return
            _vs, _chain, block_id, _h, _commit = entry
            parts = blk.make_part_set()
            self.store.save_block(blk, parts, nxt.last_commit)
            # a commit-verified block that fails to apply is a deterministic
            # local fault (bad app or corrupt state), not a peer fault
            try:
                self.state, _retain = self.block_exec.apply_block(
                    self.state, block_id, blk)
            except Exception as e:
                raise FatalSyncError(
                    f"apply_block failed at {blk.header.height}: {e}") from e
            self.pool.pop()
            self.blocks_synced += 1

    async def _punish(self, peer_ids, reason: str) -> None:
        if self.switch is None:
            return
        for pid in peer_ids:
            peer = self.switch.peers.get(pid)
            if peer is not None:
                await self.switch.stop_peer_for_error(peer, reason)
