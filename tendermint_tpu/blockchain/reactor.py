"""Block-sync ("fast sync") reactor — channel 0x40
(reference blockchain/v0/reactor.go:51; pool routine at :255).

TPU-first difference from the reference: the reference verifies ONE commit per
pool-routine iteration (VerifyCommitLight of block N against N+1's
LastCommit, one scalar ed25519 verify per signature). Here a contiguous
window of downloaded blocks is verified as ONE device batch
(types.validator_set.verify_commit_light_batched) whenever the window shares
a validator set (header.validators_hash equality — the hash commits to the
full set), which is the common case; heights where the set changes fall back
to per-block verification. This is baseline config #5 (10k-block replay at
1000 validators).

The apply plane is a 2-deep stage pipeline:

    stage A (worker thread)   | window N:  hash blocks (part sets, block
                              | IDs), precompute both signature planes,
                              | batched light-verify
    stage B (event loop)      | window N-1: ABCI exec + per-window batched
                              | store writes

While window N-1 is in stage B, window N's stage A runs concurrently on the
executor (device dispatch and OpenSSL release the GIL, so the verify
round-trip hides under ABCI exec). The single ``_prepared`` slot is the
explicit backpressure bound: at most one window of lookahead, prepared
results are consumed in strict height order, and a prepared window is
discarded whenever the pool or validator set moved underneath it (redo,
valset change), so apply order and peer-punish semantics are identical to
the unpipelined loop.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..p2p import BLOCKCHAIN_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from ..state import BlockExecutor
from ..state.state import State
from ..store import BlockStore
from ..types.basic import BlockID
from ..types.block import Block
from ..crypto import phases
from ..crypto.batch import BatchVerifier, precomputed_verdicts
from ..libs.faults import faults
from ..libs.metrics import BlocksyncMetrics, Registry
from ..libs.peerscore import PeerScoreboard
from ..libs.trace import tracer
from ..types.validator_set import verify_commit_light_batched
from .msgs import (
    BlockRequest,
    BlockResponse,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_msg,
    encode_msg,
)
from .pool import BlockPool

logger = logging.getLogger("tmtpu.blockchain")


class FatalSyncError(Exception):
    """A deterministic local fault during block application: the reference
    panics here (v0/reactor.go ApplyBlock err); we stop the sync loop and
    propagate so the node halts and restart replay reconciles."""


# verify/apply at most this many blocks per batch; bounds device batch size
# (10k validators x 64 blocks = 640k sigs would exceed one comfortable batch)
VERIFY_WINDOW = 16
# window precompute engages at/above this many candidate signatures (both
# planes); below it the per-block path is cheaper and compile-free
PRECOMPUTE_MIN_SIGS = 2048
POLL_INTERVAL = 0.01
STATUS_UPDATE_INTERVAL = 10.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0


@dataclass
class _PreparedWindow:
    """Stage-A output for one verify window, handed to the apply stage."""

    start_height: int
    vals_hash: bytes          # validator-set hash the window was gated on
    window: list              # [(block, peer_id)] — the pairs + commit carrier
    pairs: list               # [(blk, peer_id, next_blk, next_peer_id)]
    entries: list             # verify_commit_light_batched inputs
    results: list             # per-entry verdicts (None or exception)
    pre: Optional[dict] = field(default=None, repr=False)  # verdict memo


class BlockchainReactor(Reactor):
    def __init__(self, state: State, block_exec: BlockExecutor,
                 block_store: BlockStore, fast_sync: bool,
                 consensus_reactor=None, on_fatal=None):
        super().__init__("BLOCKCHAIN")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.pool = BlockPool(max(self.store.height(), state.last_block_height) + 1)
        self._pool_task: Optional[asyncio.Task] = None
        # called with the exception on a fatal (deterministic) sync fault;
        # the node wires this to shut itself down (the reference panics)
        self.on_fatal = on_fatal
        self.synced = asyncio.Event()  # set on switch-to-consensus
        self.blocks_synced = 0
        # the pipeline's single lookahead slot (backpressure bound = 1)
        self._prepared: Optional[_PreparedWindow] = None
        # per-stage histograms + pipeline counters (libs/metrics.py
        # BlocksyncMetrics). The node rebinds this to its shared registry so
        # the series land on /metrics; standalone reactors (bench, tests)
        # keep this private set. bench.py derives the old stage_times
        # breakdown from the histogram sums via stage_breakdown().
        self.metrics = BlocksyncMetrics(Registry())
        # untrusted-provider scoring (libs/peerscore.py): a bad block is a
        # strike — exponential backoff keeps the offender out of the pool,
        # ban_threshold strikes disconnect it. Threshold 2 (not 1): over a
        # Byzantine wire a single tampered response may be the LINK lying,
        # not the peer; a repeat offender is disconnected either way.
        self.scoreboard = PeerScoreboard(
            ban_threshold=int(
                os.environ.get("TMTPU_BLOCKSYNC_BAN_THRESHOLD") or 2),
            seed=faults.seed, name="blocksync",
            # every ban path (bad_block, bad_encoding, unsolicited) counts;
            # node.py re-points this when it rebinds self.metrics
            bans_counter=self.metrics.peer_bans_total)

    def stage_breakdown(self) -> dict:
        """The bench-facing view of the stage metrics: cumulative seconds
        per stage + window counters — the same numbers the old stage_times
        dict accumulated, now derived from the metric set."""
        m = self.metrics
        return {
            "hash_s": m.stage_seconds.sum_value("hash"),
            "verify_s": m.stage_seconds.sum_value("verify"),
            "store_s": m.stage_seconds.sum_value("store"),
            "abci_s": m.stage_seconds.sum_value("exec"),
            "pipelined_windows": int(m.pipelined_windows_total.value()),
            "inline_windows": int(m.inline_windows_total.value()),
        }

    @staticmethod
    def exec_phase_breakdown(wall_t0: float, wall_t1: float) -> dict:
        """Phase decomposition of the EXEC plane over a wall-clock window:
        state/execution.py records one ``plane="exec"`` segment per applied
        block (validate=pack, tx execution=in-flight, commit+persist=fetch),
        so the same interval-union accounting that profiles the device
        verify plane decomposes block execution — bench's ``exec`` config
        reports the in-flight (execute) share vs validate/commit overhead.
        Stage A's verify-commit(H+1) runs concurrently with these segments;
        its time lives in ``stage_breakdown()`` verify_s, not here."""
        recs = [r for r in phases.recent_segments()
                if r.get("plane") == "exec"
                and wall_t0 <= r["t0"] and r["t_end"] <= wall_t1]
        return phases.phase_breakdown(recs, wall_t0, wall_t1)

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5,
                                  send_queue_capacity=1000,
                                  recv_message_capacity=10 * 1024 * 1024)]

    async def start(self) -> None:
        # idempotent: Switch.start() starts every registered reactor, and the
        # node/state-sync paths may call start again — two concurrent pool
        # routines would double-apply blocks
        if self.fast_sync:
            if self._pool_task is None:
                self._pool_task = asyncio.create_task(self._pool_routine())
                self._pool_task.add_done_callback(self._pool_done)
        else:
            self.synced.set()

    async def switch_to_fast_sync(self, state: State) -> None:
        """(reactor.go SwitchToFastSync) enter fast sync from a state-synced
        state: re-seed the pool at the bootstrapped height and start."""
        self.state = state
        self.fast_sync = True
        self.synced.clear()
        self._prepared = None  # any lookahead was for the old pool
        self.pool = BlockPool(state.last_block_height + 1)
        if self._pool_task is None:
            self._pool_task = asyncio.create_task(self._pool_routine())
            self._pool_task.add_done_callback(self._pool_done)

    def _pool_done(self, task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.critical("block sync died: %s", exc)
            if self.on_fatal is not None:
                self.on_fatal(exc)

    async def stop(self) -> None:
        if self._pool_task is not None:
            self._pool_task.cancel()
            self._pool_task = None

    # -- peer lifecycle -----------------------------------------------------

    async def add_peer(self, peer: Peer) -> None:
        # advertise our range so the peer can sync from us (reactor.go AddPeer)
        peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(
            StatusResponse(self.store.height(), self.store.base())))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self.pool.remove_peer(peer.id)

    # -- inbound ------------------------------------------------------------

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_msg(msg_bytes)
        except Exception:
            # a garbled payload on the blocksync channel is a strike before
            # the switch drops the link — over a Byzantine wire the
            # scoreboard is how repeat offenders get recognized across
            # reconnects
            self.scoreboard.record_failure(peer.id, "bad_encoding")
            raise
        if isinstance(msg, BlockRequest):
            block = self.store.load_block(msg.height)
            if block is not None:
                # blocksync.bad_block (libs/faults.py): this node serves a
                # tampered block part/commit — the fetching victim's real
                # decode + commit-verification path must catch it and
                # strike/ban us via its scoreboard
                payload = faults.mutate("blocksync.bad_block",
                                        encode_msg(BlockResponse(block)))
                peer.try_send(BLOCKCHAIN_CHANNEL, payload)
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(NoBlockResponse(msg.height)))
        elif isinstance(msg, StatusRequest):
            peer.try_send(BLOCKCHAIN_CHANNEL, encode_msg(
                StatusResponse(self.store.height(), self.store.base())))
        elif isinstance(msg, StatusResponse):
            # a provider in backoff/ban stays out of the pool — the status
            # broadcast would otherwise re-admit it the moment we struck it
            if not (self.scoreboard.banned(peer.id)
                    or self.scoreboard.in_backoff(peer.id)):
                self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, BlockResponse):
            status = self.pool.add_block(peer.id, msg.block)
            if status == "unsolicited":
                # never requested from anyone: peer error, not a free
                # bandwidth vector (reference reactor treats it as such).
                # "stale" (timed-out/reassigned request arriving late) is an
                # honest slow peer and is silently dropped.
                logger.warning("unsolicited block h=%d from %s",
                               msg.block.header.height, peer.id)
                self.scoreboard.record_failure(peer.id, "unsolicited")
                if self.switch is not None:
                    await self.switch.stop_peer_for_error(
                        peer, f"unsolicited block at {msg.block.header.height}")
        elif isinstance(msg, NoBlockResponse):
            self.pool.no_block(peer.id, msg.height)

    # -- the sync loop (reactor.go:255 poolRoutine) --------------------------

    async def _pool_routine(self) -> None:
        last_status = 0.0
        last_switch_check = 0.0
        self.switch and self._broadcast_status_request()
        while True:
            try:
                now = time.monotonic()
                if now - last_status > STATUS_UPDATE_INTERVAL:
                    self._broadcast_status_request()
                    last_status = now
                for peer_id, height in self.pool.schedule_requests():
                    peer = self.switch.peers.get(peer_id) if self.switch else None
                    if peer is not None:
                        peer.try_send(BLOCKCHAIN_CHANNEL,
                                      encode_msg(BlockRequest(height)))
                await self._process_window()
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    if self.pool.is_caught_up():
                        logger.info("fast sync complete at height %d (%d blocks)",
                                    self.state.last_block_height, self.blocks_synced)
                        self._switch_to_consensus()
                        return
                await asyncio.sleep(POLL_INTERVAL)
            except asyncio.CancelledError:
                raise
            except FatalSyncError:
                logger.critical("fatal block-sync error; halting sync loop")
                raise
            except Exception:
                logger.exception("pool routine error")
                await asyncio.sleep(0.1)

    def _broadcast_status_request(self) -> None:
        if self.switch is not None:
            self.switch.broadcast(BLOCKCHAIN_CHANNEL, encode_msg(StatusRequest()))

    def _switch_to_consensus(self) -> None:
        self.synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(self.state)

    async def _process_window(self) -> None:
        """Verify+apply a contiguous run of downloaded blocks, pipelined.

        Block N's canonical commit is block N+1's LastCommit, so a run of
        k+1 blocks yields k verifiable (block, commit) pairs. All pairs whose
        headers commit to the CURRENT validator set are verified as one
        device batch; the rest of the run waits for the state to advance.

        Steady state: the window was already verified by the previous
        iteration's prepare-ahead (stage A ran while the previous window
        applied); this iteration applies it and concurrently prepares the
        next one.
        """
        loop = asyncio.get_running_loop()
        prep = self._take_prepared()
        if prep is None:
            window = self.pool.peek_window(VERIFY_WINDOW + 1)
            if len(window) < 2:
                return
            cur_vals_hash = self.state.validators.hash()
            pairs = self._select_pairs(window, cur_vals_hash)
            if not pairs:
                # the very next block claims a different valset: its commit
                # can't be checked against our state -> bad block
                # (validate_block would reject it anyway); redo from here.
                first, first_peer = window[0]
                await self._punish(self.pool.redo(first.header.height),
                                   "block valset hash mismatch")
                return
            # off-loop: a cold backend compile or a big host batch inside
            # the loop would stall RPC/p2p liveness for the whole node
            prep = await loop.run_in_executor(
                None, self._stage_a, window, pairs, cur_vals_hash,
                self.state.last_validators, self.state.validators,
                self.state.chain_id)
            self.metrics.inline_windows_total.inc()
        else:
            self.metrics.pipelined_windows_total.inc()

        # 2-deep pipeline: kick off stage A for the NEXT window on a worker
        # thread before this window's apply starts. Snapshot the pre-apply
        # valset NOW — the prepared result is only consumed if the apply
        # leaves the set's membership unchanged (_take_prepared re-checks).
        next_task = None
        next_start = prep.start_height + len(prep.pairs)
        nwindow = self.pool.peek_from(next_start, VERIFY_WINDOW + 1)
        if len(nwindow) >= 2:
            npairs = self._select_pairs(nwindow, prep.vals_hash)
            if npairs:
                # prepared-ahead windows verify every block against the
                # CURRENT set: the run is gated on hash equality, so the
                # first block's signing set (its previous height's valset)
                # has identical membership and powers
                next_task = loop.run_in_executor(
                    None, self._stage_a, nwindow, npairs, prep.vals_hash,
                    self.state.validators, self.state.validators,
                    self.state.chain_id)
        elif next_start + 1 <= self.pool.max_peer_height():
            # download plane starved the lookahead: a peer advertises the
            # next window's pair (next_start and its commit carrier) but the
            # blocks weren't here when stage A wanted to start. Chain
            # exhaustion (end of sync) is NOT a stall.
            self.metrics.lookahead_stalls_total.inc()
        try:
            await self._apply_window(prep)
        except BaseException:
            # a failed window N aborts N+1 cleanly: nothing from the
            # lookahead may outlive the fault
            if next_task is not None:
                next_task.cancel()
            self._prepared = None
            raise
        if next_task is not None:
            try:
                self._prepared = await next_task
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prepare-ahead failed; next window will "
                                 "re-verify inline")
                self._prepared = None

    def _take_prepared(self) -> Optional[_PreparedWindow]:
        """Consume the lookahead slot — only if the world it was computed
        against still holds: same next height, same validator-set hash, and
        the pool still holds the very same block objects (a redo swaps in
        re-downloads from other peers)."""
        prep, self._prepared = self._prepared, None
        if prep is None:
            return None
        if (prep.start_height != self.pool.height
                or prep.vals_hash != self.state.validators.hash()):
            self.metrics.stale_window_discards_total.inc()
            return None
        window = self.pool.peek_from(prep.start_height, len(prep.window))
        if len(window) < len(prep.window):
            self.metrics.stale_window_discards_total.inc()
            return None
        for (blk, peer_id), (pblk, ppeer_id) in zip(window, prep.window):
            if blk is not pblk or peer_id != ppeer_id:
                self.metrics.stale_window_discards_total.inc()
                return None
        return prep

    @staticmethod
    def _select_pairs(window, cur_vals_hash) -> List[Tuple[Block, str, Block, str]]:
        pairs: List[Tuple[Block, str, Block, str]] = []  # (blk, peer, next, npeer)
        for (blk, peer_id), (nxt, npeer_id) in zip(window, window[1:]):
            if blk.header.validators_hash != cur_vals_hash:
                break  # validator set changes mid-window: verify after advance
            pairs.append((blk, peer_id, nxt, npeer_id))
        return pairs

    # -- stage A: hash + verify (worker thread) -----------------------------

    def _stage_a(self, window, pairs, vals_hash, first_vals, vals,
                 chain_id) -> _PreparedWindow:
        """Everything that can run before the window's first ABCI call:
        part-set construction, block hashing, sign-bytes assembly, the
        dual-plane signature precompute, and the batched light verify. All
        results memoize on the immutable block/commit instances, so the
        apply stage re-derives none of it."""
        with tracer.span("verify_window", height=pairs[0][0].header.height,
                         n_blocks=len(pairs)):
            # height-tag the window's device segments: the seg_pack/
            # seg_dispatch/seg_fetch spans and phase records carry the
            # first height so trace tooling can line device-pipeline
            # occupancy up against the consensus stage timeline
            with phases.telemetry(height=pairs[0][0].header.height):
                return self._stage_a_inner(window, pairs, vals_hash,
                                           first_vals, vals, chain_id)

    def _stage_a_inner(self, window, pairs, vals_hash, first_vals, vals,
                       chain_id) -> _PreparedWindow:
        t0 = time.perf_counter()
        entries = []
        for blk, _p, nxt, _np in pairs:
            parts_header = blk.make_part_set().header()
            block_id = BlockID(blk.hash(), parts_header)
            entries.append((vals, chain_id, block_id, blk.header.height,
                            nxt.last_commit))
        t1 = time.perf_counter()

        # Pre-verify the window's OTHER signature plane in the same scope:
        # apply_block -> validate_block re-checks each block's LastCommit
        # with the full VerifyCommit predicate (state/validation.py:55,
        # reference state/validation.go:72). Verified one commit at a time
        # that is a full-dispatch-latency device call per block; batched
        # here, the apply loop's verify_commit hits precomputed verdicts and
        # the whole window costs one device round-trip for BOTH planes.
        pre = self._precompute_last_commit_verdicts(pairs, first_vals, vals,
                                                    chain_id)
        token = precomputed_verdicts.set(pre) if pre is not None else None
        try:
            results = verify_commit_light_batched(entries)
        finally:
            if token is not None:
                precomputed_verdicts.reset(token)
        t2 = time.perf_counter()
        self.metrics.stage_seconds.labels("hash").observe(t1 - t0)
        self.metrics.stage_seconds.labels("verify").observe(t2 - t1)
        return _PreparedWindow(
            start_height=pairs[0][0].header.height, vals_hash=vals_hash,
            window=window[:len(pairs) + 1], pairs=pairs, entries=entries,
            results=results, pre=pre)

    def _precompute_last_commit_verdicts(self, pairs, first_vals, vals,
                                         chain_id) -> "Optional[dict]":
        """(pk, sign_bytes, sig) -> verdict for every candidate signature the
        window will verify — the light entries above AND each block's
        LastCommit full-commit candidates. Returns None when the window's
        LastCommits span a validator-set change (the per-block fallback is
        correct there; _select_pairs already bounds pairs to one set for
        the light plane)."""
        try:
            return self._precompute_inner(pairs, first_vals, vals, chain_id)
        except Exception as e:
            # peer data is untrusted here (nothing has validated these
            # blocks yet): ANY malformed shape — last_commit=None, odd sig
            # sizes — falls back to the per-block path, whose per-entry
            # error handling turns bad blocks into pool.redo + punish
            # instead of wedging the pool routine
            logger.debug("window precompute skipped: %s", e)
            return None

    def _precompute_inner(self, pairs, first_vals, vals,
                          chain_id) -> "Optional[dict]":
        first_h = pairs[0][0].header.height
        # small-net windows (few validators or a short tail) stay on the
        # per-block path: doubling a tiny batch buys nothing and must not
        # push it over the device-routing threshold (a cold XLA compile in a
        # fresh node process would dwarf the verification itself)
        if any(hasattr(blk.last_commit, "agg_sig")
               or hasattr(nxt.last_commit, "agg_sig")
               for blk, _p, nxt, _np in pairs):
            # aggregated commits verify via one pairing in
            # verify_commit_light_batched, not an ed25519 device batch —
            # nothing to precompute here
            return None
        n_sigs = sum(len(blk.last_commit.signatures) if blk.last_commit else 0
                     for blk, _p, _n, _np in pairs) * 2
        if n_sigs < PRECOMPUTE_MIN_SIGS:
            return None
        bv = BatchVerifier(plane="light")
        keys: List[Tuple[bytes, bytes, bytes]] = []

        def _add(pub, msg, sig):
            bv.add(pub, msg, sig)
            keys.append((pub.bytes(), msg, sig))

        for blk, _p, nxt, _np in pairs:
            # block h's LastCommit was signed by the valset of h-1: the first
            # window block checks against the caller's first_vals (the live
            # last_validators when preparing inline; the current set when
            # preparing ahead, where the hash gate makes them equal), later
            # ones against the (stable) current set. A stale guess here can
            # only miss the memo and re-dispatch — never mis-verify.
            fv = first_vals if blk.header.height == first_h else vals
            lc = blk.last_commit
            if lc is not None and len(lc.signatures):
                if len(lc.signatures) != fv.size():
                    return None  # shape mismatch: let validate_block decide
                sb = lc.vote_sign_bytes_all(chain_id)
                for idx, cs in enumerate(lc.signatures):
                    if not cs.absent():
                        _add(fv.validators[idx].pub_key, sb[idx],
                             cs.signature)
            # the light plane of THIS window (nxt.last_commit rows) shares
            # the batch: one device call covers both planes. Candidate rule
            # MUST mirror verify_commit_light_batched (validator_set.py):
            # for_block sigs keyed by (pk, vote_sign_bytes_all row, sig) —
            # any divergence makes BatchVerifier miss the precomputed dict
            # and silently re-dispatch, not mis-verify (all-or-nothing hit)
            sbn = nxt.last_commit.vote_sign_bytes_all(chain_id)
            for idx, cs in enumerate(nxt.last_commit.signatures):
                if cs.for_block() and idx < vals.size():
                    _add(vals.validators[idx].pub_key, sbn[idx], cs.signature)
        if not keys:
            return None
        _, verdicts = bv.verify()
        return {t: bool(v) for t, v in zip(keys, verdicts)}

    # -- stage B: apply (event loop, strict height order) -------------------

    async def _apply_window(self, prep: _PreparedWindow) -> None:
        with tracer.span("apply_window", height=prep.start_height,
                         n_blocks=len(prep.pairs)):
            await self._apply_window_inner(prep)

    async def _apply_window_inner(self, prep: _PreparedWindow) -> None:
        token = (precomputed_verdicts.set(prep.pre)
                 if prep.pre is not None else None)
        st = self.metrics.stage_seconds
        applied = 0
        t_flush = None
        try:
            # every write the window produces — block parts, commits, seen
            # commits, ABCI responses, per-height validator/param records,
            # the state record — lands in ONE write-batch per store, flushed
            # at scope exit (also on error: staged writes describe blocks
            # whose ABCI commit already happened)
            with self.store.window_batch(), \
                    self.block_exec.state_store.window_batch():
                for (blk, peer_id, nxt, npeer_id), err, entry in zip(
                        prep.pairs, prep.results, prep.entries):
                    if err is not None:
                        logger.warning("invalid block/commit at height %d: %s",
                                       blk.header.height, err)
                        bad = self.pool.redo(blk.header.height)
                        bad.update({peer_id, npeer_id})
                        await self._punish(
                            bad, f"bad block at {blk.header.height}: {err}")
                        return
                    _vs, _chain, block_id, _h, _commit = entry
                    t0 = time.perf_counter()
                    parts = blk.make_part_set()
                    self.store.save_block(blk, parts, nxt.last_commit)
                    t1 = time.perf_counter()
                    # a commit-verified block that fails to apply is a
                    # deterministic local fault (bad app or corrupt state),
                    # not a peer fault
                    try:
                        self.state, _retain = self.block_exec.apply_block(
                            self.state, block_id, blk)
                    except Exception as e:
                        raise FatalSyncError(
                            f"apply_block failed at {blk.header.height}: {e}"
                        ) from e
                    t2 = time.perf_counter()
                    st.labels("store").observe(t1 - t0)
                    st.labels("exec").observe(t2 - t1)
                    self.pool.pop()
                    self.blocks_synced += 1
                    applied += 1
                t_flush = time.perf_counter()
        finally:
            if t_flush is not None:
                # the batched per-window DB flush is store-stage time too
                st.labels("store").observe(time.perf_counter() - t_flush)
            if applied:
                self.metrics.window_blocks.observe(applied)
            if token is not None:
                precomputed_verdicts.reset(token)

    async def _punish(self, peer_ids, reason: str) -> None:
        """Strike every suspected provider on the scoreboard; disconnect
        only those the scoreboard bans (ban_threshold strikes). First
        offenders sit out an exponential backoff instead — pool.redo
        already dropped them, and the backoff check in StatusResponse
        handling keeps them out until it lapses."""
        self.metrics.sync_retries_total.inc()  # the redo behind this punish
        for pid in set(peer_ids):
            if self.scoreboard.banned(pid):
                continue  # already banned (and disconnected) earlier
            if not self.scoreboard.record_failure(pid, "bad_block"):
                logger.info("block provider %s struck (%s); backing off",
                            pid[:8], reason)
                continue
            # (the scoreboard's bans_counter already counted the ban)
            if self.switch is not None:
                peer = self.switch.peers.get(pid)
                if peer is not None:
                    await self.switch.stop_peer_for_error(peer, reason)
        # re-discover remaining providers right away: the redo emptied the
        # pool's view of the offenders and sync should not idle a full
        # STATUS_UPDATE_INTERVAL before asking who else can serve
        self._broadcast_status_request()
