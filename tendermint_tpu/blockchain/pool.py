"""BlockPool: schedules block downloads from peers during fast sync
(reference blockchain/v0/pool.go:63 BlockPool, :193 per-height bpRequester).

Redesigned for asyncio: instead of one goroutine per height, a single
scheduler pass (driven by the reactor's pool routine) keeps up to
``max_pending`` outstanding height requests assigned across known peers,
re-assigning on timeout or peer failure. Downloaded blocks accumulate until
the reactor pops contiguous runs for windowed (batched) commit verification.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..types.block import Block

logger = logging.getLogger("tmtpu.blockchain")

# Reference pool.go consts (requestIntervalMS, maxTotalRequesters=600,
# maxPendingRequestsPerPeer=20); sized down for asyncio polling granularity.
MAX_PENDING = 64
MAX_PENDING_PER_PEER = 16
REQUEST_TIMEOUT = 15.0  # seconds before a pending request is re-assigned
MIN_RECV_RATE = 0  # rate-based peer ban not enforced in-proc


@dataclass
class _PeerInfo:
    base: int = 0
    height: int = 0
    pending: int = 0
    timeouts: int = 0


@dataclass
class _Request:
    height: int
    peer_id: str
    sent_at: float
    block: Optional[Block] = None


class BlockPool:
    def __init__(self, start_height: int):
        self.height = start_height  # next height to pop
        self._peers: Dict[str, _PeerInfo] = {}
        self._requests: Dict[int, _Request] = {}
        self._max_peer_height = 0
        self._started_at = time.monotonic()

    # -- peer bookkeeping (pool.go:290 SetPeerRange) ------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        info = self._peers.setdefault(peer_id, _PeerInfo())
        info.base, info.height = base, height
        self._max_peer_height = max(self._max_peer_height, height)

    def remove_peer(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)
        for h, req in list(self._requests.items()):
            if req.peer_id == peer_id and req.block is None:
                del self._requests[h]

    def max_peer_height(self) -> int:
        return self._max_peer_height

    def is_caught_up(self) -> bool:
        """(pool.go:168 IsCaughtUp)"""
        if not self._peers:
            return False
        # reference: caught up when within 1 of the best peer
        # (pool.go IsCaughtUp: height >= maxPeerHeight - 1)
        return self.height >= max(1, self._max_peer_height - 1)

    # -- scheduling ---------------------------------------------------------

    def schedule_requests(self) -> List[Tuple[str, int]]:
        """One scheduler pass; -> [(peer_id, height)] requests to send now.

        Covers [self.height, ..) up to MAX_PENDING outstanding, re-assigning
        requests that timed out. Peers are chosen randomly among those whose
        advertised range covers the height and that have pending capacity.
        """
        now = time.monotonic()
        to_send: List[Tuple[str, int]] = []

        # re-assign timed-out requests
        for h, req in list(self._requests.items()):
            if req.block is None and now - req.sent_at > REQUEST_TIMEOUT:
                info = self._peers.get(req.peer_id)
                if info is not None:
                    info.pending -= 1
                    info.timeouts += 1
                del self._requests[h]

        horizon = self.height + MAX_PENDING
        if self._max_peer_height:
            horizon = min(horizon, self._max_peer_height + 1)
        for h in range(self.height, horizon):
            if h in self._requests:
                continue
            peer_id = self._pick_peer(h)
            if peer_id is None:
                continue
            self._requests[h] = _Request(h, peer_id, now)
            self._peers[peer_id].pending += 1
            to_send.append((peer_id, h))
        return to_send

    def _pick_peer(self, height: int) -> Optional[str]:
        candidates = [
            pid for pid, info in self._peers.items()
            if info.base <= height <= info.height
            and info.pending < MAX_PENDING_PER_PEER
        ]
        return random.choice(candidates) if candidates else None

    # -- block arrival (pool.go AddBlock) -----------------------------------

    def add_block(self, peer_id: str, block: Block) -> str:
        """Accept a block matching an outstanding request from peer_id.

        Returns "added", "stale" (a legitimate-but-late response: the height
        was processed already or the request timed out and was reassigned —
        NOT a peer fault), or "unsolicited" (we never asked this peer for
        anything near this height — a spam/bandwidth fault, reference
        reactor stops the peer).
        """
        h = block.header.height
        req = self._requests.get(h)
        if req is None or req.peer_id != peer_id or req.block is not None:
            # reference pool.go AddBlock: only a height far (>100) from the
            # pool's cursor is a peer fault; anything near it is a late
            # response to a request we timed out/deleted/reassigned
            if abs(h - self.height) > 100:
                return "unsolicited"
            return "stale"
        req.block = block
        info = self._peers.get(peer_id)
        if info is not None:
            info.pending -= 1
        return "added"

    def no_block(self, peer_id: str, height: int) -> None:
        req = self._requests.get(height)
        if req is not None and req.peer_id == peer_id and req.block is None:
            info = self._peers.get(peer_id)
            if info is not None:
                info.pending -= 1
            del self._requests[height]

    # -- consumption --------------------------------------------------------

    def peek_window(self, max_blocks: int) -> List[Tuple[Block, str]]:
        """Contiguous (block, provider peer) run starting at self.height."""
        return self.peek_from(self.height, max_blocks)

    def peek_from(self, start_height: int, max_blocks: int) -> List[Tuple[Block, str]]:
        """Contiguous (block, provider peer) run starting at an arbitrary
        height ≥ self.height — the apply pipeline peeks the NEXT window's
        blocks while the current one is still applying."""
        out: List[Tuple[Block, str]] = []
        h = start_height
        while len(out) < max_blocks:
            req = self._requests.get(h)
            if req is None or req.block is None:
                break
            out.append((req.block, req.peer_id))
            h += 1
        return out

    def pop(self) -> None:
        """(pool.go PopRequest) advance past self.height."""
        self._requests.pop(self.height, None)
        self.height += 1

    def redo(self, height: int) -> Set[str]:
        """(pool.go RedoRequest) drop all blocks from the peers that served
        [height..] and re-request; -> peer ids to punish."""
        bad: Set[str] = set()
        for h, req in list(self._requests.items()):
            if h >= height and req.block is not None:
                bad.add(req.peer_id)
        for h, req in list(self._requests.items()):
            if req.peer_id in bad:
                if req.block is None:
                    info = self._peers.get(req.peer_id)
                    if info is not None:
                        info.pending -= 1
                del self._requests[h]
        for pid in bad:
            self._peers.pop(pid, None)
        return bad
