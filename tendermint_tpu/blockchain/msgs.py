"""Block-sync wire messages (reference proto/tendermint/blockchain/types.proto
Message oneof: block_request=1, no_block_response=2, block_response=3,
status_request=4, status_response=5).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protowire as pw
from ..types.block import Block


@dataclass
class BlockRequest:
    height: int


@dataclass
class NoBlockResponse:
    height: int


@dataclass
class BlockResponse:
    block: Block


@dataclass
class StatusRequest:
    pass


@dataclass
class StatusResponse:
    height: int
    base: int


def encode_msg(msg) -> bytes:
    w = pw.Writer()
    if isinstance(msg, BlockRequest):
        b = pw.Writer()
        b.varint(1, msg.height)
        w.message(1, b.finish())
    elif isinstance(msg, NoBlockResponse):
        b = pw.Writer()
        b.varint(1, msg.height)
        w.message(2, b.finish())
    elif isinstance(msg, BlockResponse):
        b = pw.Writer()
        b.message(1, msg.block.encode())
        w.message(3, b.finish())
    elif isinstance(msg, StatusRequest):
        w.message(4, pw.Writer().finish())
    elif isinstance(msg, StatusResponse):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.base)
        w.message(5, b.finish())
    else:
        raise ValueError(f"unknown blockchain message {type(msg)}")
    return w.finish()


def decode_msg(data: bytes):
    fields = list(pw.iter_fields(data))
    if len(fields) != 1:
        raise ValueError("blockchain Message must have exactly one oneof field")
    fn, _wt, body = fields[0]
    d = pw.fields_dict(body)

    def iv(n, default=0):
        vals = d.get(n)
        return pw.varint_to_int64(vals[0]) if vals else default

    if fn == 1:
        return BlockRequest(iv(1))
    if fn == 2:
        return NoBlockResponse(iv(1))
    if fn == 3:
        vals = d.get(1)
        if not vals:
            raise ValueError("BlockResponse without block")
        return BlockResponse(Block.decode(vals[0]))
    if fn == 4:
        return StatusRequest()
    if fn == 5:
        return StatusResponse(iv(1), iv(2))
    raise ValueError(f"unknown blockchain Message field {fn}")
