"""BlockID, PartSetHeader, signed-message enums, time constants.

Wire parity: proto/tendermint/types/types.proto (PartSetHeader field 1/2,
BlockID field 1/2 with non-nullable part_set_header — always emitted, see
types.pb.go:1233-1256).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

from ..libs import protowire as pw

# Go's zero time.Time (Jan 1, year 1 UTC) in unix-nanoseconds; the timestamp
# carried by absent CommitSigs (reference types/block.go NewCommitSigAbsent).
ZERO_TIME_NS = -62_135_596_800 * 1_000_000_000

MAX_HASH_SIZE = 32
BLOCK_PART_SIZE_BYTES = 65536  # types/part_set.go:23


class SignedMsgType(IntEnum):
    UNKNOWN = 0
    PREVOTE = 1
    PRECOMMIT = 2
    PROPOSAL = 32


class BlockIDFlag(IntEnum):
    UNKNOWN = 0
    ABSENT = 1
    COMMIT = 2
    NIL = 3


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.total)
        w.bytes(2, self.hash)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "PartSetHeader":
        total, h = 0, b""
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                total = v & 0xFFFFFFFF  # uint32 on the wire; don't let an
                # oversized varint crash key() downstream
            elif fn == 2:
                h = v
        return PartSetHeader(total, h)

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative Total")
        if len(self.hash) not in (0, MAX_HASH_SIZE):
            raise ValueError("wrong Hash size")


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        """Non-nil and fully specified (reference types/block.go BlockID.IsComplete)."""
        return (
            len(self.hash) == MAX_HASH_SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == MAX_HASH_SIZE
        )

    def encode(self) -> bytes:
        w = pw.Writer()
        w.bytes(1, self.hash)
        w.message(2, self.part_set_header.encode())  # non-nullable: always
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "BlockID":
        h, psh = b"", PartSetHeader()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                h = v
            elif fn == 2:
                psh = PartSetHeader.decode(v)
        return BlockID(h, psh)

    def validate_basic(self) -> None:
        if len(self.hash) not in (0, MAX_HASH_SIZE):
            raise ValueError("wrong Hash size")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key for vote tallies (reference types/block.go BlockID.Key)."""
        return self.hash + self.part_set_header.total.to_bytes(4, "big") + self.part_set_header.hash
