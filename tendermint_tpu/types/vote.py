"""Vote (reference types/vote.go).

Sign-bytes are the canonical length-delimited proto (canonical.py); `verify`
is THE scalar hot call the batched TPU path replaces (vote.go:147-152).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import crypto
from ..crypto import schemes
from ..libs import protowire as pw
from .basic import BlockID, SignedMsgType, ZERO_TIME_NS
from .canonical import vote_sign_bytes
from .errors import ErrVoteInvalidSignature, ErrVoteInvalidValidatorAddress

# MaxVotesCount bounds validator-set size for sanity checks (types/vote.go:24).
MAX_VOTES_COUNT = 10000

MAX_SIGNATURE_SIZE = 64


@dataclass
class Vote:
    type: SignedMsgType
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        ts = self.timestamp_ns
        if (self.type == SignedMsgType.PRECOMMIT
                and schemes.for_chain(chain_id).zero_precommit_ts):
            # aggregated chains sign one shared precommit payload; the real
            # timestamp still travels in the Vote for the commit's median
            ts = schemes.AGG_ZERO_TS_NS
        return vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, ts
        )

    def verify(self, chain_id: str, pub_key: crypto.PubKey) -> None:
        if pub_key.address() != self.validator_address:
            raise ErrVoteInvalidValidatorAddress()
        if not pub_key.verify_signature(self.sign_bytes(chain_id), self.signature):
            raise ErrVoteInvalidSignature()

    def verify_with(self, chain_id: str, pub_key: crypto.PubKey,
                    verifier) -> None:
        """Same decisions as :meth:`verify`, signature check routed through a
        verifier (micro-batch cache / device path; vote_set.go:205 hot call)."""
        if pub_key.address() != self.validator_address:
            raise ErrVoteInvalidValidatorAddress()
        if not verifier.verify(pub_key, self.sign_bytes(chain_id), self.signature):
            raise ErrVoteInvalidSignature()

    def copy(self) -> "Vote":
        return Vote(self.type, self.height, self.round, self.block_id,
                    self.timestamp_ns, self.validator_address,
                    self.validator_index, self.signature)

    def validate_basic(self) -> None:
        if self.type not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        self.block_id.validate_basic()
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != crypto.ADDRESS_SIZE:
            raise ValueError(
                f"expected ValidatorAddress size to be {crypto.ADDRESS_SIZE} bytes, "
                f"got {len(self.validator_address)} bytes"
            )
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if len(self.signature) == 0:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError(f"signature is too big (max: {MAX_SIGNATURE_SIZE})")

    # -- proto (types.proto Vote) -----------------------------------------

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, int(self.type))
        w.varint(2, self.height)
        w.varint(3, self.round)
        w.message(4, self.block_id.encode())
        w.message(5, pw.timestamp(self.timestamp_ns))
        w.bytes(6, self.validator_address)
        w.varint(7, self.validator_index)
        w.bytes(8, self.signature)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Vote":
        type_ = SignedMsgType.UNKNOWN
        height = round_ = val_index = 0
        block_id = BlockID()
        ts = ZERO_TIME_NS
        val_addr = sig = b""
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                type_ = SignedMsgType(v)
            elif fn == 2:
                height = pw.varint_to_int64(v)
            elif fn == 3:
                round_ = pw.varint_to_int64(v)
            elif fn == 4:
                block_id = BlockID.decode(v)
            elif fn == 5:
                ts = pw.parse_timestamp(v)
            elif fn == 6:
                val_addr = v
            elif fn == 7:
                val_index = pw.varint_to_int64(v)
            elif fn == 8:
                sig = v
        return Vote(type_, height, round_, block_id, ts, val_addr, val_index, sig)
