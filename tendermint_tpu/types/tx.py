"""Transactions (reference types/tx.go): Tx = raw bytes, hashed with SHA-256."""

from __future__ import annotations

import hashlib
from typing import List, Sequence

from ..crypto import merkle


def tx_hash(tx: bytes) -> bytes:
    """tmhash.Sum (types/tx.go:29)."""
    return hashlib.sha256(tx).digest()


def txs_hash(txs: Sequence[bytes]) -> bytes:
    """Merkle root over per-tx hashes (types/tx.go:47)."""
    return merkle.hash_from_byte_slices([tx_hash(t) for t in txs])


def compute_proto_size_overhead(body_len: int, field_count: int = 1) -> int:
    """Varint framing overhead for a repeated bytes field (types/tx.go ComputeProtoSizeForTxs)."""
    from ..libs.protowire import encode_varint

    return field_count + len(encode_varint(body_len))


def txs_bytes_size(txs: Sequence[bytes]) -> int:
    """Proto-encoded size of the Data message holding these txs."""
    return sum(len(t) + compute_proto_size_overhead(len(t)) for t in txs)
