"""VoteSet: tally for one (height, round, type) (reference types/vote_set.go:61).

Semantics preserved exactly: dedup by validator index, conflicting-vote
detection (→ evidence), only-first-quorum maj23 selection, peer-claimed maj23
tracking. The signature check inside add_vote stays scalar (votes arrive one
at a time over gossip); commit-at-once paths use the batched verifier in
ValidatorSet.verify_commit*.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..libs.bits import BitArray
from .basic import BlockID, BlockIDFlag, SignedMsgType
from .block import Commit, CommitSig
from .errors import ErrVoteConflictingVotes
from .validator_set import ValidatorSet
from .vote import Vote


class VoteSetError(Exception):
    pass


class ErrVoteNonDeterministicSignatureSet(VoteSetError):
    pass


@dataclass
class _BlockVotes:
    """Votes for one particular block (vote_set.go blockVotes)."""

    peer_maj23: bool
    bit_array: BitArray
    votes: List[Optional[Vote]]
    sum: int = 0

    @staticmethod
    def new(peer_maj23: bool, num_validators: int) -> "_BlockVotes":
        return _BlockVotes(peer_maj23, BitArray(num_validators), [None] * num_validators, 0)

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Optional[Vote]:
        if 0 <= idx < len(self.votes):
            return self.votes[idx]
        return None


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int,
                 signed_msg_type: SignedMsgType, val_set: ValidatorSet,
                 verifier=None):
        if height == 0:
            raise ValueError("Cannot make VoteSet for height == 0, doesn't make sense")
        # signature verifier seam (crypto/vote_batcher.py): None = plain
        # host scalar verify, BatchVoteVerifier = micro-batched device path
        # with one-shot verdict cache fed by the reactor's preverification
        self.verifier = verifier
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self._mtx = threading.Lock()
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: List[Optional[Vote]] = [None] * val_set.size()
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: Dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: Dict[str, BlockID] = {}

    # -- adding votes ------------------------------------------------------

    def add_vote(self, vote: Optional[Vote]) -> bool:
        """Returns True if added. Duplicate → False. Conflicting →
        ErrVoteConflictingVotes (vote_set.go:145)."""
        with self._mtx:
            return self._add_vote(vote)

    def _add_vote(self, vote: Optional[Vote]) -> bool:
        if vote is None:
            raise VoteSetError("nil vote")
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0:
            raise VoteSetError("index < 0: invalid validator index")
        if len(val_addr) == 0:
            raise VoteSetError("empty address: invalid validator address")
        if (vote.height != self.height or vote.round != self.round
                or vote.type != self.signed_msg_type):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, but got "
                f"{vote.height}/{vote.round}/{vote.type}: unexpected step"
            )
        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteSetError(
                f"cannot find validator {val_index} in valSet of size "
                f"{self.val_set.size()}: invalid validator index"
            )
        if val_addr != lookup_addr:
            raise VoteSetError(
                f"vote.ValidatorAddress ({val_addr.hex()}) does not match address "
                f"({lookup_addr.hex()}) for vote.ValidatorIndex ({val_index})"
            )

        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # duplicate
            raise ErrVoteNonDeterministicSignatureSet(
                f"existing vote: {existing}; new vote: {vote}"
            )

        if self.verifier is None:
            vote.verify(self.chain_id, val.pub_key)
        else:
            vote.verify_with(self.chain_id, val.pub_key, self.verifier)

        added, conflicting = self._add_verified_vote(vote, block_key, val.voting_power)
        if conflicting is not None:
            raise ErrVoteConflictingVotes(conflicting, vote)
        if not added:
            raise VoteSetError("Expected to add non-conflicting vote")
        return added

    def _get_vote(self, val_index: int, block_key: bytes) -> Optional[Vote]:
        existing = self.votes[val_index] if val_index < len(self.votes) else None
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        by_block = self.votes_by_block.get(block_key)
        if by_block is not None:
            return by_block.get_by_index(val_index)
        return None

    def _add_verified_vote(self, vote: Vote, block_key: bytes,
                           voting_power: int) -> Tuple[bool, Optional[Vote]]:
        val_index = vote.validator_index
        conflicting: Optional[Vote] = None

        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise VoteSetError("addVerifiedVote does not expect duplicate votes")
            conflicting = existing
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power

        by_block = self.votes_by_block.get(block_key)
        if by_block is not None:
            if conflicting is not None and not by_block.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            by_block = _BlockVotes.new(False, self.val_set.size())
            self.votes_by_block[block_key] = by_block

        orig_sum = by_block.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        by_block.add_verified_vote(vote, voting_power)

        if orig_sum < quorum <= by_block.sum:
            if self.maj23 is None:
                self.maj23 = vote.block_id
                for i, v in enumerate(by_block.votes):
                    if v is not None:
                        self.votes[i] = v
        return True, conflicting

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim of 2/3 majority for a block (vote_set.go:313)."""
        with self._mtx:
            block_key = block_id.key()
            existing = self.peer_maj23s.get(peer_id)
            if existing is not None:
                if existing == block_id:
                    return
                raise VoteSetError(
                    f"setPeerMaj23: Received conflicting blockID from peer {peer_id}. "
                    f"Got {block_id}, expected {existing}"
                )
            self.peer_maj23s[peer_id] = block_id
            by_block = self.votes_by_block.get(block_key)
            if by_block is not None:
                if by_block.peer_maj23:
                    return
                by_block.peer_maj23 = True
            else:
                self.votes_by_block[block_key] = _BlockVotes.new(True, self.val_set.size())

    # -- queries -----------------------------------------------------------

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        with self._mtx:
            by_block = self.votes_by_block.get(block_id.key())
            if by_block is not None:
                return by_block.bit_array.copy()
            return None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        with self._mtx:
            if 0 <= val_index < len(self.votes):
                return self.votes[val_index]
            return None

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        with self._mtx:
            idx, _ = self.val_set.get_by_address(address)
            if idx >= 0:
                return self.votes[idx]
            return None

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23 is not None

    def two_thirds_majority(self) -> Tuple[BlockID, bool]:
        """(blockID, True) if 2/3 majority reached (vote_set.go:449)."""
        with self._mtx:
            if self.maj23 is not None:
                return self.maj23, True
            return BlockID(), False

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    def size(self) -> int:
        return self.val_set.size()

    def list_votes(self) -> List[Vote]:
        with self._mtx:
            return [v for v in self.votes if v is not None]

    # -- commit building ---------------------------------------------------

    def make_commit(self) -> Commit:
        """Requires an unambiguous 2/3 majority (vote_set.go:612).  On
        aggregated chains the maj23 precommits fold into one
        AggregatedCommit instead of a CommitSig list."""
        if self.signed_msg_type != SignedMsgType.PRECOMMIT:
            raise VoteSetError("Cannot MakeCommit() unless VoteSet.Type is PRECOMMIT")
        from ..crypto import schemes

        with self._mtx:
            if self.maj23 is None:
                raise VoteSetError("Cannot MakeCommit() unless a blockhash has +2/3")
            if schemes.aggregated(self.chain_id):
                return self._make_aggregated_commit()
            commit_sigs = []
            for v in self.votes:
                cs = vote_to_commit_sig(v)
                # Sig for a different block than maj23 → excluded (vote_set.go:629).
                if cs.for_block() and v.block_id != self.maj23:
                    cs = CommitSig.new_absent()
                commit_sigs.append(cs)
            return Commit(self.height, self.round, self.maj23, commit_sigs)

    def _make_aggregated_commit(self):
        """Called with the lock held, maj23 set.  Every maj23 precommit
        signed the SAME zero-timestamp payload (Vote.sign_bytes on
        aggregated chains), so the signatures fold into one 48-byte BLS
        aggregate; nil/other-block votes simply stay out of the bitmap.
        The commit timestamp is the voting-power-weighted median of the
        included votes' (wire-carried) timestamps — the same WeightedMedian
        state.median_time computes for CommitSig lists."""
        from ..crypto import bls12381 as bls
        from .block import AggregatedCommit

        signers = BitArray(self.val_set.size())
        sigs: List[bytes] = []
        weighted = []
        total_power = 0
        for i, v in enumerate(self.votes):
            if v is None or not v.block_id.is_complete() or v.block_id != self.maj23:
                continue
            signers.set_index(i, True)
            sigs.append(v.signature)
            power = self.val_set.validators[i].voting_power
            weighted.append((v.timestamp_ns, power))
            total_power += power
        agg_sig = bls.aggregate(sigs)
        weighted.sort()
        median = total_power // 2
        ts = 0
        for t, power in weighted:
            if median <= power:  # types/time/time.go:50 WeightedMedian
                ts = t
                break
            median -= power
        return AggregatedCommit(self.height, self.round, self.maj23, [],
                                signers=signers, agg_sig=agg_sig,
                                timestamp_ns=ts)


def vote_to_commit_sig(v: Optional[Vote]) -> CommitSig:
    """Vote → CommitSig (types/vote.go:62)."""
    if v is None:
        return CommitSig.new_absent()
    if v.block_id.is_complete():
        flag = BlockIDFlag.COMMIT
    elif v.block_id.is_zero():
        flag = BlockIDFlag.NIL
    else:
        raise ValueError(f"Invalid vote {v} - expected BlockID to be either empty or complete")
    return CommitSig(flag, v.validator_address, v.timestamp_ns, v.signature)
