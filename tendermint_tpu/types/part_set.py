"""PartSet: a block split into 65536-byte merkle-proven parts for gossip
(reference types/part_set.go:23,150,166).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto import merkle
from ..libs import protowire as pw
from ..libs.bits import BitArray
from .basic import BLOCK_PART_SIZE_BYTES, PartSetHeader


def encode_proof(p: merkle.Proof) -> bytes:
    """tendermint.crypto.Proof (proto/tendermint/crypto/proof.proto)."""
    w = pw.Writer()
    w.varint(1, p.total)
    w.varint(2, p.index)
    w.bytes(3, p.leaf_hash)
    for aunt in p.aunts:
        w.bytes(4, aunt)
    return w.finish()


def decode_proof(data: bytes) -> merkle.Proof:
    total = index = 0
    leaf = b""
    aunts: List[bytes] = []
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            total = pw.varint_to_int64(v)
        elif fn == 2:
            index = pw.varint_to_int64(v)
        elif fn == 3:
            leaf = v
        elif fn == 4:
            aunts.append(v)
    return merkle.Proof(total=total, index=index, leaf_hash=leaf, aunts=aunts)


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if self.index < 0:
            raise ValueError("negative Index")
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(f"too big: {len(self.bytes_)} bytes, max: {BLOCK_PART_SIZE_BYTES}")
        if self.proof.total <= 0 or self.proof.index != self.index or len(self.proof.leaf_hash) != 32:
            raise ValueError("wrong proof")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.index)
        w.bytes(2, self.bytes_)
        w.message(3, encode_proof(self.proof))
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Part":
        index = 0
        bytes_ = b""
        proof = merkle.Proof(0, 0, b"")
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                index = pw.varint_to_int64(v)
            elif fn == 2:
                bytes_ = v
            elif fn == 3:
                proof = decode_proof(v)
        return Part(index, bytes_, proof)


class PartSet:
    """Either built complete from data, or assembled incrementally from a header."""

    def __init__(self, total: int, hash_: bytes):
        self.total = total
        self._hash = hash_
        self.parts: List[Optional[Part]] = [None] * total
        self.parts_bit_array = BitArray(total)
        self.count = 0
        self.byte_size = 0

    @staticmethod
    def from_data(data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """Split + merkle-prove (part_set.go:166 NewPartSetFromData)."""
        total = (len(data) + part_size - 1) // part_size
        if total == 0:
            total = 1
        chunks = [data[i * part_size:(i + 1) * part_size] for i in range(total)]
        proofs = merkle.proofs_from_byte_slices(chunks)
        root = proofs[0].compute_root() if proofs else merkle.hash_from_byte_slices([])
        ps = PartSet(total, root)
        for i, chunk in enumerate(chunks):
            part = Part(i, chunk, proofs[i])
            ps.parts[i] = part
            ps.parts_bit_array.set_index(i, True)
            ps.count += 1
            ps.byte_size += len(chunk)
        return ps

    @staticmethod
    def from_header(header: PartSetHeader) -> "PartSet":
        return PartSet(header.total, header.hash)

    def header(self) -> PartSetHeader:
        return PartSetHeader(self.total, self._hash)

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header() == header

    def hash(self) -> bytes:
        return self._hash

    def is_complete(self) -> bool:
        return self.count == self.total

    def add_part(self, part: Part) -> bool:
        """Merkle-verify then store (part_set.go AddPart). Duplicate → False."""
        if part.index >= self.total:
            raise ValueError("error part set unexpected index")
        if self.parts[part.index] is not None:
            return False
        if not part.proof.verify(self._hash, part.bytes_):
            raise ValueError("error part set invalid proof")
        self.parts[part.index] = part
        self.parts_bit_array.set_index(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, index: int) -> Optional[Part]:
        return self.parts[index]

    def get_reader(self) -> bytes:
        """Reassembled bytes; only valid when complete."""
        if not self.is_complete():
            raise ValueError("cannot read incomplete part set")
        return b"".join(p.bytes_ for p in self.parts)  # type: ignore[union-attr]
