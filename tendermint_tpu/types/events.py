"""Typed event names + query helpers (reference types/events.go)."""

from __future__ import annotations

# Event type values (types/events.go:16-40)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_LOCK = "Lock"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_UNLOCK = "Unlock"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VOTE = "Vote"

# Reserved composite-key namespace (types/events.go:100+)
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event_type: str) -> str:
    return f"{EVENT_TYPE_KEY}='{event_type}'"


QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
QUERY_NEW_BLOCK_HEADER = query_for_event(EVENT_NEW_BLOCK_HEADER)
QUERY_TX = query_for_event(EVENT_TX)
QUERY_NEW_ROUND_STEP = query_for_event(EVENT_NEW_ROUND_STEP)
QUERY_VOTE = query_for_event(EVENT_VOTE)
QUERY_VALIDATOR_SET_UPDATES = query_for_event(EVENT_VALIDATOR_SET_UPDATES)
