"""Validator (reference types/validator.go).

`bytes_for_hash` is the SimpleValidator proto encoding merkle-ized by
ValidatorSet.Hash (reference types/validator.go:117-133).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from .. import crypto
from ..libs import protowire as pw

MAX_TOTAL_VOTING_POWER = (2**63 - 1) // 8  # types/validator_set.go:25
PRIORITY_WINDOW_SIZE_FACTOR = 2  # types/validator_set.go:30

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


def safe_add_clip(a: int, b: int) -> int:
    c = a + b
    return min(max(c, INT64_MIN), INT64_MAX)


def safe_sub_clip(a: int, b: int) -> int:
    c = a - b
    return min(max(c, INT64_MIN), INT64_MAX)


def safe_mul(a: int, b: int) -> "tuple[int, bool]":
    c = a * b
    if c > INT64_MAX or c < INT64_MIN:
        return 0, True
    return c, False


def pubkey_proto_bytes(pub: crypto.PubKey) -> bytes:
    """tendermint.crypto.PublicKey oneof encoding (proto/tendermint/crypto/keys.proto).

    Cached on the key instance: PubKey objects are immutable and shared
    across Validator copies (Validator.copy passes the reference), while
    state persistence and valset hashing re-encode every validator several
    times per block — profiling showed this as the hottest proto call."""
    cached = getattr(pub, "_proto_bytes", None)
    if cached is not None:
        return cached
    w = pw.Writer()
    if pub.type_name == crypto.ED25519_TYPE:
        w.bytes(1, pub.bytes())
    elif pub.type_name == "secp256k1":
        w.bytes(2, pub.bytes())
    elif pub.type_name == crypto.BLS12381_TYPE:
        # same oneof field the ABCI codec uses for validator updates
        w.bytes(3, pub.bytes())
    else:
        raise ValueError(f"unsupported pubkey type {pub.type_name!r}")
    out = w.finish()
    try:
        # frozen-dataclass keys need the object.__setattr__ side door;
        # equality/hash use declared fields only, so the cache is invisible
        object.__setattr__(pub, "_proto_bytes", out)
    except AttributeError:
        pass  # __slots__ keys just skip the cache
    return out


def pubkey_from_proto(data: bytes) -> crypto.PubKey:
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            return crypto.Ed25519PubKey(v)
        if fn == 2:
            return crypto.pubkey_from_type_and_bytes("secp256k1", v)
        if fn == 3:
            return crypto.pubkey_from_type_and_bytes(crypto.BLS12381_TYPE, v)
    raise ValueError("empty PublicKey proto")


@dataclass
class Validator:
    address: bytes
    pub_key: crypto.PubKey
    voting_power: int
    proposer_priority: int = 0

    def copy(self) -> "Validator":
        return Validator(self.address, self.pub_key, self.voting_power, self.proposer_priority)

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break to the lower address (validator.go:64)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("cannot compare identical validators")

    def bytes_for_hash(self) -> bytes:
        """SimpleValidator proto encoding (validator.go:117)."""
        w = pw.Writer()
        w.message(1, pubkey_proto_bytes(self.pub_key))  # nullable ptr but always set
        w.varint(2, self.voting_power)
        return w.finish()

    def encode(self) -> bytes:
        """Full Validator proto (validator.proto:15-20) for wire/storage.

        The address/pubkey/power prefix is immutable for a validator's
        lifetime and cached; only the proposer-priority varint (which
        rotates every height) is re-encoded. State persistence encodes
        whole 1000-validator sets several times per block, so this is a
        measured hot path, not speculation."""
        # hold the pub_key OBJECT and compare with `is`: keying on
        # id(self.pub_key) is an id-recycling hazard — a replaced key object
        # can land on the freed key's address and silently serve the old
        # encoding. The stored reference also pins the object, so the id
        # can't be recycled while the cache lives.
        cached = self.__dict__.get("_enc_prefix")
        if (cached is None or cached[0] is not self.pub_key
                or cached[1] != self.voting_power):
            w = pw.Writer()
            w.bytes(1, self.address)
            w.message(2, pubkey_proto_bytes(self.pub_key))
            w.varint(3, self.voting_power)
            cached = (self.pub_key, self.voting_power, w.finish())
            self.__dict__["_enc_prefix"] = cached
        pp = self.proposer_priority
        if pp == 0:  # proto3 zero omission, like Writer.varint
            return cached[2]
        return cached[2] + pw.tag(4, pw.WIRE_VARINT) + pw.encode_varint(pp)

    @staticmethod
    def decode(data: bytes) -> "Validator":
        address = b""
        pub_key = None
        voting_power = 0
        priority = 0
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                address = v
            elif fn == 2:
                pub_key = pubkey_from_proto(v)
            elif fn == 3:
                voting_power = pw.varint_to_int64(v)
            elif fn == 4:
                priority = pw.varint_to_int64(v)
        if pub_key is None:
            raise ValueError("validator missing pubkey")
        return Validator(address, pub_key, voting_power, priority)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != crypto.ADDRESS_SIZE:
            raise ValueError("validator address is the wrong size")


def new_validator(pub_key: crypto.PubKey, voting_power: int) -> Validator:
    return Validator(pub_key.address(), pub_key, voting_power)
