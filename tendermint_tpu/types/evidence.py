"""Evidence of byzantine behaviour (reference types/evidence.go).

DuplicateVoteEvidence: two conflicting votes from one validator at one H/R.
LightClientAttackEvidence: a conflicting light block + byzantine validators.
EvidenceList hash merkle-izes the proto `Bytes()` of each item (evidence.go:431).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle
from ..libs import protowire as pw
from .basic import ZERO_TIME_NS
from .vote import Vote

MAX_EVIDENCE_BYTES = 444  # types/evidence.go MaxEvidenceBytes (duplicate vote)


class Evidence:
    """Common interface (types/evidence.go:22)."""

    def abci_evidence_type(self) -> str:
        raise NotImplementedError

    def height(self) -> int:
        raise NotImplementedError

    def time_ns(self) -> int:
        raise NotImplementedError

    def bytes(self) -> bytes:
        """UNWRAPPED proto encoding (reference Bytes() = ToProto().Marshal(),
        evidence.go:90-98 — no oneof envelope). This is what EvidenceList.Hash
        and Evidence.Hash consume."""
        raise NotImplementedError

    def wrapped(self) -> bytes:
        """Evidence oneof envelope, for the EvidenceList wire message."""
        raise NotImplementedError

    def hash(self) -> bytes:
        raise NotImplementedError

    def validate_basic(self) -> None:
        raise NotImplementedError


@dataclass
class DuplicateVoteEvidence(Evidence):
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = ZERO_TIME_NS

    @staticmethod
    def new(vote1: Vote, vote2: Vote, block_time_ns: int, val_set) -> "Optional[DuplicateVoteEvidence]":
        """Orders votes by BlockID key (evidence.go:49)."""
        if vote1 is None or vote2 is None or val_set is None:
            return None
        idx, val = val_set.get_by_address(vote1.validator_address)
        if idx == -1:
            return None
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return DuplicateVoteEvidence(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp_ns=block_time_ns,
        )

    def abci_evidence_type(self) -> str:
        return "DUPLICATE_VOTE"

    def height(self) -> int:
        return self.vote_a.height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def bytes(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.vote_a.encode())
        w.message(2, self.vote_b.encode())
        w.varint(3, self.total_voting_power)
        w.varint(4, self.validator_power)
        w.message(5, pw.timestamp(self.timestamp_ns))
        return w.finish()

    def wrapped(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.bytes())  # oneof sum: field 1
        return w.finish()

    def hash(self) -> bytes:
        return hashlib.sha256(self.bytes()).digest()

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("empty duplicate vote evidence")
        if len(self.vote_a.signature) == 0 or len(self.vote_b.signature) == 0:
            raise ValueError("missing signature")
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError("duplicate votes in invalid order")

    @staticmethod
    def decode_body(data: bytes) -> "DuplicateVoteEvidence":
        vote_a = vote_b = None
        tvp = vp = 0
        ts = ZERO_TIME_NS
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                vote_a = Vote.decode(v)
            elif fn == 2:
                vote_b = Vote.decode(v)
            elif fn == 3:
                tvp = pw.varint_to_int64(v)
            elif fn == 4:
                vp = pw.varint_to_int64(v)
            elif fn == 5:
                ts = pw.parse_timestamp(v)
        return DuplicateVoteEvidence(vote_a, vote_b, tvp, vp, ts)


@dataclass
class LightClientAttackEvidence(Evidence):
    """A conflicting light block shown to a light client (evidence.go:190)."""

    conflicting_block: object  # LightBlock (light module); needs .signed_header.header
    common_height: int
    byzantine_validators: List = field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = ZERO_TIME_NS

    def abci_evidence_type(self) -> str:
        return "LIGHT_CLIENT_ATTACK"

    def height(self) -> int:
        return self.common_height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def conflicting_header_hash(self) -> bytes:
        return self.conflicting_block.signed_header.header.hash()

    def hash(self) -> bytes:
        """tmhash over block hash || varint(common height) (evidence.go:302)."""
        varint = _go_put_varint(self.common_height)
        bz = bytearray(32 + len(varint))
        h = self.conflicting_header_hash()
        bz[:31] = h[:31]  # reference copies into [:tmhash.Size-1] (quirk kept)
        bz[32:] = varint
        return hashlib.sha256(bytes(bz)).digest()

    def bytes(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.conflicting_block.encode())
        w.varint(2, self.common_height)
        for val in self.byzantine_validators:
            w.message(3, val.encode())
        w.varint(4, self.total_voting_power)
        w.message(5, pw.timestamp(self.timestamp_ns))
        return w.finish()

    def wrapped(self) -> bytes:
        w = pw.Writer()
        w.message(2, self.bytes())  # oneof sum: field 2
        return w.finish()

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("conflicting block is nil")
        if self.common_height <= 0:
            raise ValueError("negative or zero common height")


def _go_put_varint(v: int) -> bytes:
    """encoding/binary PutVarint = zigzag varint."""
    return pw.encode_zigzag(v)


def evidence_list_hash(evidence: List[Evidence]) -> bytes:
    return merkle.hash_from_byte_slices([ev.bytes() for ev in evidence])


def encode_evidence_list(evidence: List[Evidence]) -> bytes:
    """EvidenceList proto message (evidence.proto:37) — oneof-wrapped items."""
    w = pw.Writer()
    for ev in evidence:
        w.message(1, ev.wrapped())
    return w.finish()


def decode_evidence_list(data: bytes) -> List[Evidence]:
    out: List[Evidence] = []
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            out.append(decode_evidence(v))
    return out


def decode_evidence(data: bytes) -> Evidence:
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            return DuplicateVoteEvidence.decode_body(v)
        if fn == 2:
            return _decode_lcae(v)
    raise ValueError("unknown evidence type")


def _decode_lcae(data: bytes) -> LightClientAttackEvidence:
    from .light_block import LightBlock
    from .validator import Validator

    cb = None
    common_height = tvp = 0
    byz: List = []
    ts = ZERO_TIME_NS
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            cb = LightBlock.decode(v)
        elif fn == 2:
            common_height = pw.varint_to_int64(v)
        elif fn == 3:
            byz.append(Validator.decode(v))
        elif fn == 4:
            tvp = pw.varint_to_int64(v)
        elif fn == 5:
            ts = pw.parse_timestamp(v)
    return LightClientAttackEvidence(cb, common_height, byz, tvp, ts)
