"""GenesisDoc (reference types/genesis.go): JSON load/validate/save."""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .. import crypto
from .params import ConsensusParams, default_consensus_params
from .validator import Validator

MAX_CHAIN_ID_LEN = 50


@dataclass
class GenesisValidator:
    pub_key: crypto.PubKey
    power: int
    name: str = ""
    address: bytes = b""
    # BLS proof of possession; mandatory when the chain's signature scheme
    # is bls12381 (the rogue-key gate), absent otherwise
    pop: bytes = b""

    def __post_init__(self):
        if not self.address:
            self.address = self.pub_key.address()


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: Optional[ConsensusParams] = None
    validators: List[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validate_and_complete(self) -> None:
        """(types/genesis.go ValidateAndComplete)"""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = default_consensus_params()
        else:
            self.consensus_params.validate_basic()
        bls_chain = (self.consensus_params.signature.scheme == "bls12381")
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"the genesis file cannot contain validators with no voting power: {v}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i} in the genesis file")
            if bls_chain:
                # key registration: a BLS validator key enters the set only
                # with a verified proof of possession (rogue-key defense)
                if v.pub_key.type_name != "bls12381":
                    raise ValueError(
                        f"validator {i}: bls12381 chain requires bls12381 "
                        f"keys, got {v.pub_key.type_name}")
                from ..crypto import bls12381 as _bls

                if not v.pop:
                    raise ValueError(
                        f"validator {i}: missing BLS proof of possession")
                _bls.register_key(v.pub_key.bytes(), v.pop)
        if self.genesis_time_ns == 0:
            self.genesis_time_ns = time.time_ns()

    def validator_hash(self) -> bytes:
        vals = [Validator(v.pub_key.address(), v.pub_key, v.power) for v in self.validators]
        from .validator_set import ValidatorSet

        return ValidatorSet(vals).hash()

    def to_json(self) -> str:
        def enc_params(p: ConsensusParams) -> dict:
            return {
                "block": {
                    "max_bytes": str(p.block.max_bytes),
                    "max_gas": str(p.block.max_gas),
                    "time_iota_ms": str(p.block.time_iota_ms),
                },
                "evidence": {
                    "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
                    "max_age_duration": str(p.evidence.max_age_duration_ns),
                    "max_bytes": str(p.evidence.max_bytes),
                },
                "validator": {"pub_key_types": p.validator.pub_key_types},
                "version": {"app_version": str(p.version.app_version)},
            }

        def enc_params_full(p: ConsensusParams) -> dict:
            out = enc_params(p)
            if not p.signature.is_default:
                # omitted for default chains: genesis JSON stays byte-for-
                # byte what it was before the scheme plane existed
                out["signature"] = {
                    "scheme": p.signature.scheme,
                    "aggregate_commits": p.signature.aggregate_commits,
                }
            return out

        doc = {
            "genesis_time": self.genesis_time_ns,
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": enc_params_full(self.consensus_params or default_consensus_params()),
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": {"type": v.pub_key.type_name, "value": v.pub_key.bytes().hex()},
                    "power": str(v.power),
                    "name": v.name,
                    **({"pop": v.pop.hex()} if v.pop else {}),
                }
                for v in self.validators
            ],
            "app_hash": self.app_hash.hex().upper(),
            "app_state": json.loads(self.app_state.decode("utf-8")) if self.app_state else {},
        }
        return json.dumps(doc, indent=2, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "GenesisDoc":
        doc = json.loads(s)
        params = None
        if "consensus_params" in doc and doc["consensus_params"]:
            cp = doc["consensus_params"]
            from .params import (BlockParams, EvidenceParams, SignatureParams,
                                 ValidatorParams, VersionParams)

            sig = cp.get("signature") or {}
            params = ConsensusParams(
                BlockParams(int(cp["block"]["max_bytes"]), int(cp["block"]["max_gas"]),
                            int(cp["block"].get("time_iota_ms", 1000))),
                EvidenceParams(int(cp["evidence"]["max_age_num_blocks"]),
                               int(cp["evidence"]["max_age_duration"]),
                               int(cp["evidence"].get("max_bytes", 1048576))),
                ValidatorParams(list(cp["validator"]["pub_key_types"])),
                VersionParams(int(cp.get("version", {}).get("app_version", 0))),
                SignatureParams(sig.get("scheme", "ed25519"),
                                bool(sig.get("aggregate_commits", False))),
            )
        validators = []
        for v in doc.get("validators") or []:
            pub = crypto.pubkey_from_type_and_bytes(
                v["pub_key"]["type"], bytes.fromhex(v["pub_key"]["value"])
            )
            validators.append(GenesisValidator(
                pub_key=pub, power=int(v["power"]), name=v.get("name", ""),
                address=bytes.fromhex(v["address"]) if v.get("address") else b"",
                pop=bytes.fromhex(v["pop"]) if v.get("pop") else b"",
            ))
        gd = GenesisDoc(
            chain_id=doc["chain_id"],
            genesis_time_ns=int(doc.get("genesis_time", 0)),
            initial_height=int(doc.get("initial_height", 1)),
            consensus_params=params,
            validators=validators,
            app_hash=bytes.fromhex(doc.get("app_hash", "")),
            app_state=json.dumps(doc.get("app_state", {})).encode("utf-8"),
        )
        gd.validate_and_complete()
        return gd

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def from_file(path: str) -> "GenesisDoc":
        with open(path) as f:
            return GenesisDoc.from_json(f.read())

    def hash(self) -> bytes:
        return hashlib.sha256(self.to_json().encode("utf-8")).digest()
