"""EventBus: typed envelope over libs.pubsub (reference types/event_bus.go:33).

Publishes consensus/tx events with indexable composite keys; RPC WS
subscriptions and the tx indexer both ride subscriptions on this bus.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..libs.pubsub import PubSubServer, Query, Subscription
from . import events as tme
from .block import Block, Header
from .vote import Vote


@dataclass
class EventDataNewBlock:
    block: Block
    block_id: object
    result_begin_block: object = None
    result_end_block: object = None


@dataclass
class EventDataNewBlockHeader:
    header: Header
    result_begin_block: object = None
    result_end_block: object = None


@dataclass
class EventDataTx:
    height: int
    index: int
    tx: bytes
    result: object


@dataclass
class EventDataNewEvidence:
    evidence: object
    height: int


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class EventDataNewRound:
    height: int
    round: int
    step: str
    proposer_address: bytes = b""
    proposer_index: int = -1


@dataclass
class EventDataCompleteProposal:
    height: int
    round: int
    step: str
    block_id: object = None


@dataclass
class EventDataVote:
    vote: Vote


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: List = field(default_factory=list)


def _abci_events_to_map(events) -> Dict[str, List[str]]:
    """Flatten app events into composite keys '<type>.<attr>' → values."""
    out: Dict[str, List[str]] = {}
    for ev in events or []:
        if not getattr(ev, "type", ""):
            continue
        for attr in getattr(ev, "attributes", []) or []:
            key = f"{ev.type}.{attr.key.decode('utf-8', errors='replace')}"
            out.setdefault(key, []).append(attr.value.decode("utf-8", errors="replace"))
    return out


class EventBus:
    def __init__(self):
        self._server = PubSubServer()

    # -- subscriptions --

    def subscribe(self, subscriber: str, query: str, out_capacity: int = 100) -> Subscription:
        return self._server.subscribe(subscriber, Query(query), out_capacity)

    def unsubscribe(self, subscriber: str, query: str) -> None:
        self._server.unsubscribe(subscriber, Query(query))

    def unsubscribe_all(self, subscriber: str) -> None:
        self._server.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self._server.num_clients()

    def num_client_subscriptions(self, subscriber: str) -> int:
        return self._server.num_client_subscriptions(subscriber)

    # -- publishing (event_bus.go:118+) --

    def _publish(self, event_type: str, data, extra: Optional[Dict[str, List[str]]] = None,
                 app_events=None) -> None:
        events = _abci_events_to_map(app_events)
        for k, v in (extra or {}).items():
            events.setdefault(k, []).extend(v)
        events.setdefault(tme.EVENT_TYPE_KEY, []).append(event_type)
        self._server.publish(data, events)

    def publish_event_new_block(self, block: Block, block_id, rbb, reb) -> None:
        app_events = list(getattr(rbb, "events", []) or []) + list(getattr(reb, "events", []) or [])
        self._publish(tme.EVENT_NEW_BLOCK,
                      EventDataNewBlock(block, block_id, rbb, reb),
                      {tme.BLOCK_HEIGHT_KEY: [str(block.header.height)]},
                      app_events)

    def publish_event_new_block_header(self, header: Header, rbb, reb) -> None:
        app_events = list(getattr(rbb, "events", []) or []) + list(getattr(reb, "events", []) or [])
        self._publish(tme.EVENT_NEW_BLOCK_HEADER,
                      EventDataNewBlockHeader(header, rbb, reb),
                      {tme.BLOCK_HEIGHT_KEY: [str(header.height)]},
                      app_events)

    def publish_event_new_evidence(self, evidence, height: int) -> None:
        self._publish(tme.EVENT_NEW_EVIDENCE, EventDataNewEvidence(evidence, height))

    def publish_event_tx(self, height: int, index: int, tx: bytes, result) -> None:
        import hashlib

        self._publish(tme.EVENT_TX, EventDataTx(height, index, tx, result),
                      {tme.TX_HEIGHT_KEY: [str(height)],
                       tme.TX_HASH_KEY: [hashlib.sha256(tx).hexdigest().upper()]},
                      getattr(result, "events", None))

    def publish_event_vote(self, vote: Vote) -> None:
        self._publish(tme.EVENT_VOTE, EventDataVote(vote))

    def publish_event_new_round_step(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_NEW_ROUND_STEP, rs)

    def publish_event_new_round(self, nr: EventDataNewRound) -> None:
        self._publish(tme.EVENT_NEW_ROUND, nr)

    def publish_event_complete_proposal(self, cp: EventDataCompleteProposal) -> None:
        self._publish(tme.EVENT_COMPLETE_PROPOSAL, cp)

    def publish_event_timeout_propose(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_TIMEOUT_PROPOSE, rs)

    def publish_event_timeout_wait(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_TIMEOUT_WAIT, rs)

    def publish_event_polka(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_POLKA, rs)

    def publish_event_lock(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_LOCK, rs)

    def publish_event_relock(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_RELOCK, rs)

    def publish_event_valid_block(self, rs: EventDataRoundState) -> None:
        self._publish(tme.EVENT_VALID_BLOCK, rs)

    def publish_event_validator_set_updates(self, updates) -> None:
        self._publish(tme.EVENT_VALIDATOR_SET_UPDATES,
                      EventDataValidatorSetUpdates(list(updates)))
