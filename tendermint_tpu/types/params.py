"""ConsensusParams (reference types/params.go): validation + hash.

HashConsensusParams hashes a subset proto (BlockParams.MaxBytes/MaxGas +
Evidence + Validator params) — see types/params.go HashConsensusParams.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List

from ..libs import protowire as pw

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB (types/params.go MaxBlockSizeBytes)

ABCI_PUBKEY_TYPE_ED25519 = "ed25519"
ABCI_PUBKEY_TYPE_SECP256K1 = "secp256k1"
ABCI_PUBKEY_TYPE_SR25519 = "sr25519"
ABCI_PUBKEY_TYPE_BLS12381 = "bls12381"


@dataclass
class SignatureParams:
    """Which signature scheme the chain's validators run and whether commits
    are BLS-aggregated (this repo's scheme-agnostic crypto plane; no
    reference equivalent).  The ed25519/non-aggregated default is encoded as
    *absence* — no proto field, no genesis JSON section — so every default
    chain stays byte-identical to the pre-scheme-plane format."""

    scheme: str = ABCI_PUBKEY_TYPE_ED25519
    aggregate_commits: bool = False

    @property
    def is_default(self) -> bool:
        return (self.scheme == ABCI_PUBKEY_TYPE_ED25519
                and not self.aggregate_commits)

    def encode(self) -> bytes:
        w = pw.Writer()
        w.string(1, self.scheme)
        if self.aggregate_commits:
            w.varint(2, 1)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "SignatureParams":
        p = SignatureParams()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                p.scheme = v.decode("utf-8")
            elif fn == 2:
                p.aggregate_commits = bool(v)
        return p


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1
    time_iota_ms: int = 1000  # unexposed in v0.34 but part of the proto/hash

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.max_bytes)
        w.varint(2, self.max_gas)
        w.varint(3, self.time_iota_ms)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "BlockParams":
        p = BlockParams(0, 0, 0)
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                p.max_bytes = pw.varint_to_int64(v)
            elif fn == 2:
                p.max_gas = pw.varint_to_int64(v)
            elif fn == 3:
                p.time_iota_ms = pw.varint_to_int64(v)
        return p


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000  # 48h
    max_bytes: int = 1048576

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.max_age_num_blocks)
        # google.protobuf.Duration { int64 seconds=1; int32 nanos=2 }
        seconds, nanos = divmod(self.max_age_duration_ns, 1_000_000_000)
        dw = pw.Writer()
        dw.varint(1, seconds)
        dw.varint(2, nanos)
        w.message(2, dw.finish())
        w.varint(3, self.max_bytes)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "EvidenceParams":
        p = EvidenceParams(0, 0, 0)
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                p.max_age_num_blocks = pw.varint_to_int64(v)
            elif fn == 2:
                p.max_age_duration_ns = pw.parse_timestamp(v)  # same layout
            elif fn == 3:
                p.max_bytes = pw.varint_to_int64(v)
        return p


@dataclass
class ValidatorParams:
    pub_key_types: List[str] = field(default_factory=lambda: [ABCI_PUBKEY_TYPE_ED25519])

    def encode(self) -> bytes:
        w = pw.Writer()
        for t in self.pub_key_types:
            w.string(1, t)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "ValidatorParams":
        types_ = [v.decode("utf-8") for fn, _wt, v in pw.iter_fields(data) if fn == 1]
        return ValidatorParams(types_)


@dataclass
class VersionParams:
    app_version: int = 0

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.app_version)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "VersionParams":
        p = VersionParams()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                p.app_version = v
        return p


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    signature: SignatureParams = field(default_factory=SignatureParams)

    def hash(self) -> bytes:
        """HashConsensusParams (types/params.go): sha256 of HashedParams proto
        {block_max_bytes=1, block_max_gas=2}."""
        w = pw.Writer()
        w.varint(1, self.block.max_bytes)
        w.varint(2, self.block.max_gas)
        return hashlib.sha256(w.finish()).digest()

    def validate_basic(self) -> None:
        if self.block.max_bytes <= 0:
            raise ValueError(f"block.MaxBytes must be greater than 0. Got {self.block.max_bytes}")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError(
                f"block.MaxBytes is too big. {self.block.max_bytes} > {MAX_BLOCK_SIZE_BYTES}"
            )
        if self.block.max_gas < -1:
            raise ValueError(f"block.MaxGas must be greater or equal to -1. Got {self.block.max_gas}")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be greater than 0")
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be greater than 0")
        if self.evidence.max_bytes > self.block.max_bytes:
            raise ValueError("evidence.MaxBytesEvidence is greater than upper bound")
        if self.evidence.max_bytes < 0:
            raise ValueError("evidence.MaxBytes must be non negative")
        if len(self.validator.pub_key_types) == 0:
            raise ValueError("len(Validator.PubKeyTypes) must be greater than 0")
        for t in self.validator.pub_key_types:
            if t not in (ABCI_PUBKEY_TYPE_ED25519, ABCI_PUBKEY_TYPE_SECP256K1,
                         ABCI_PUBKEY_TYPE_SR25519, ABCI_PUBKEY_TYPE_BLS12381):
                raise ValueError(f"unknown pubkey type {t}")
        if self.signature.scheme not in (ABCI_PUBKEY_TYPE_ED25519,
                                         ABCI_PUBKEY_TYPE_BLS12381):
            raise ValueError(
                f"unknown signature scheme {self.signature.scheme}")
        if self.signature.aggregate_commits and \
                self.signature.scheme != ABCI_PUBKEY_TYPE_BLS12381:
            raise ValueError(
                "signature.aggregate_commits requires the bls12381 scheme")

    def update(self, updates) -> "ConsensusParams":
        """Apply ABCI EndBlock param updates (types/params.go UpdateConsensusParams)."""
        res = ConsensusParams(
            BlockParams(self.block.max_bytes, self.block.max_gas, self.block.time_iota_ms),
            EvidenceParams(self.evidence.max_age_num_blocks,
                           self.evidence.max_age_duration_ns, self.evidence.max_bytes),
            ValidatorParams(list(self.validator.pub_key_types)),
            VersionParams(self.version.app_version),
            SignatureParams(self.signature.scheme,
                            self.signature.aggregate_commits),
        )
        if updates is None:
            return res
        if updates.block is not None:
            res.block.max_bytes = updates.block.max_bytes
            res.block.max_gas = updates.block.max_gas
        if updates.evidence is not None:
            res.evidence = EvidenceParams(updates.evidence.max_age_num_blocks,
                                          updates.evidence.max_age_duration_ns,
                                          updates.evidence.max_bytes)
        if updates.validator is not None:
            res.validator = ValidatorParams(list(updates.validator.pub_key_types))
        if updates.version is not None:
            res.version = VersionParams(updates.version.app_version)
        return res

    def encode(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.block.encode())
        w.message(2, self.evidence.encode())
        w.message(3, self.validator.encode())
        w.message(4, self.version.encode())
        if not self.signature.is_default:
            # absent for default chains: pre-scheme-plane bytes unchanged
            w.message(5, self.signature.encode())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "ConsensusParams":
        p = ConsensusParams()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                p.block = BlockParams.decode(v)
            elif fn == 2:
                p.evidence = EvidenceParams.decode(v)
            elif fn == 3:
                p.validator = ValidatorParams.decode(v)
            elif fn == 4:
                p.version = VersionParams.decode(v)
            elif fn == 5:
                p.signature = SignatureParams.decode(v)
        return p


def default_consensus_params() -> ConsensusParams:
    return ConsensusParams()
