"""Proposal (reference types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protowire as pw
from .basic import BlockID, SignedMsgType, ZERO_TIME_NS
from .canonical import proposal_sign_bytes
from .vote import MAX_SIGNATURE_SIZE


@dataclass
class Proposal:
    height: int
    round: int
    pol_round: int  # -1 if no POL round
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""
    type: SignedMsgType = SignedMsgType.PROPOSAL

    def sign_bytes(self, chain_id: str) -> bytes:
        return proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round, self.block_id, self.timestamp_ns
        )

    def validate_basic(self) -> None:
        if self.type != SignedMsgType.PROPOSAL:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1:
            raise ValueError("negative POLRound (exception: -1)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError(f"expected a complete, non-empty BlockID, got: {self.block_id}")
        if len(self.signature) == 0:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError(f"signature is too big (max: {MAX_SIGNATURE_SIZE})")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, int(self.type))
        w.varint(2, self.height)
        w.varint(3, self.round)
        w.varint(4, self.pol_round)
        w.message(5, self.block_id.encode())
        w.message(6, pw.timestamp(self.timestamp_ns))
        w.bytes(7, self.signature)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Proposal":
        height = round_ = 0
        pol_round = 0
        block_id = BlockID()
        ts = ZERO_TIME_NS
        sig = b""
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 2:
                height = pw.varint_to_int64(v)
            elif fn == 3:
                round_ = pw.varint_to_int64(v)
            elif fn == 4:
                pol_round = pw.varint_to_int64(v)
            elif fn == 5:
                block_id = BlockID.decode(v)
            elif fn == 6:
                ts = pw.parse_timestamp(v)
            elif fn == 7:
                sig = v
        return Proposal(height, round_, pol_round, block_id, ts, sig)
