"""Block, Header, Commit, CommitSig, Data (reference types/block.go).

Header.Hash merkle-izes the 14 proto-encoded fields (block.go:440-475);
Commit.Hash merkle-izes CommitSig proto encodings (block.go:894-912);
Commit.vote_sign_bytes rebuilds each validator's canonical vote sign-bytes
(block.go:784-810) — the per-index payload of the batched verifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .. import crypto
from ..crypto import merkle, schemes
from ..libs import protowire as pw
from ..libs.bits import BitArray
from .basic import BlockID, BlockIDFlag, PartSetHeader, SignedMsgType, ZERO_TIME_NS
from .canonical import (
    vote_sign_bytes,
    vote_sign_bytes_batch,
    vote_sign_bytes_columns_batch,
)
from .tx import txs_hash
from .vote import MAX_SIGNATURE_SIZE, Vote

# Protocol versions (reference version/version.go:16-22).
BLOCK_PROTOCOL = 11
P2P_PROTOCOL = 8

MAX_HEADER_BYTES = 626  # types/block.go MaxHeaderBytes


def _cdc_bytes(b: bytes) -> bytes:
    """gogotypes.BytesValue wrapper, empty → empty bytes (types/encoding_helper.go:11)."""
    if not b:
        return b""
    w = pw.Writer()
    w.bytes(1, b)
    return w.finish()


def _cdc_string(s: str) -> bytes:
    if not s:
        return b""
    w = pw.Writer()
    w.string(1, s)
    return w.finish()


def _cdc_int64(v: int) -> bytes:
    if v == 0:
        return b""
    w = pw.Writer()
    w.varint(1, v)
    return w.finish()


@dataclass(frozen=True)
class Consensus:
    """Version info committed to the chain (proto/tendermint/version/types.proto)."""

    block: int = BLOCK_PROTOCOL
    app: int = 0

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.block)
        w.varint(2, self.app)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Consensus":
        block = app = 0
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                block = v
            elif fn == 2:
                app = v
        return Consensus(block, app)


@dataclass
class Header:
    version: Consensus = field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time_ns: int = ZERO_TIME_NS
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def __setattr__(self, name: str, value) -> None:
        # any field write invalidates the hash memo: headers ARE mutated
        # after construction (fill_header, decode, test tampering), and a
        # stale memo would be a consensus fault, not a perf bug
        d = self.__dict__
        if "_hash_memo" in d:
            del d["_hash_memo"]
        object.__setattr__(self, name, value)

    def hash(self) -> Optional[bytes]:
        """Merkle root of the proto-encoded fields (block.go:440), memoized
        until the next field write. The sync hot path hashes each header
        several times (BlockID assembly, store save, ABCI BeginBlock), and
        a 14-leaf merkle plus 14 proto encodes per call was measurable at
        pipeline scale."""
        if len(self.validators_hash) == 0:
            return None
        memo = self.__dict__.get("_hash_memo")
        if memo is not None:
            return memo
        h = merkle.hash_from_byte_slices([
            self.version.encode(),
            _cdc_string(self.chain_id),
            _cdc_int64(self.height),
            pw.timestamp(self.time_ns),
            self.last_block_id.encode(),
            _cdc_bytes(self.last_commit_hash),
            _cdc_bytes(self.data_hash),
            _cdc_bytes(self.validators_hash),
            _cdc_bytes(self.next_validators_hash),
            _cdc_bytes(self.consensus_hash),
            _cdc_bytes(self.app_hash),
            _cdc_bytes(self.last_results_hash),
            _cdc_bytes(self.evidence_hash),
            _cdc_bytes(self.proposer_address),
        ])
        self.__dict__["_hash_memo"] = h
        return h

    def validate_basic(self) -> None:
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Header.Height")
        if self.height == 0:
            raise ValueError("zero Header.Height")
        self.last_block_id.validate_basic()
        for name, h in (("LastCommitHash", self.last_commit_hash),
                        ("DataHash", self.data_hash),
                        ("EvidenceHash", self.evidence_hash)):
            if len(h) not in (0, 32):
                raise ValueError(f"wrong {name}")
        if len(self.proposer_address) != crypto.ADDRESS_SIZE:
            raise ValueError("invalid ProposerAddress length")
        for name, h in (("ValidatorsHash", self.validators_hash),
                        ("NextValidatorsHash", self.next_validators_hash),
                        ("ConsensusHash", self.consensus_hash),
                        ("LastResultsHash", self.last_results_hash)):
            if len(h) not in (0, 32):
                raise ValueError(f"wrong {name}")

    # -- proto (types.proto Header) ---------------------------------------

    def encode(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.version.encode())
        w.string(2, self.chain_id)
        w.varint(3, self.height)
        w.message(4, pw.timestamp(self.time_ns))
        w.message(5, self.last_block_id.encode())
        w.bytes(6, self.last_commit_hash)
        w.bytes(7, self.data_hash)
        w.bytes(8, self.validators_hash)
        w.bytes(9, self.next_validators_hash)
        w.bytes(10, self.consensus_hash)
        w.bytes(11, self.app_hash)
        w.bytes(12, self.last_results_hash)
        w.bytes(13, self.evidence_hash)
        w.bytes(14, self.proposer_address)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Header":
        h = Header()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                h.version = Consensus.decode(v)
            elif fn == 2:
                h.chain_id = v.decode("utf-8")
            elif fn == 3:
                h.height = pw.varint_to_int64(v)
            elif fn == 4:
                h.time_ns = pw.parse_timestamp(v)
            elif fn == 5:
                h.last_block_id = BlockID.decode(v)
            elif fn == 6:
                h.last_commit_hash = v
            elif fn == 7:
                h.data_hash = v
            elif fn == 8:
                h.validators_hash = v
            elif fn == 9:
                h.next_validators_hash = v
            elif fn == 10:
                h.consensus_hash = v
            elif fn == 11:
                h.app_hash = v
            elif fn == 12:
                h.last_results_hash = v
            elif fn == 13:
                h.evidence_hash = v
            elif fn == 14:
                h.proposer_address = v
        return h


@dataclass
class CommitSig:
    block_id_flag: BlockIDFlag = BlockIDFlag.ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = ZERO_TIME_NS
    signature: bytes = b""

    @staticmethod
    def new_absent() -> "CommitSig":
        return CommitSig(BlockIDFlag.ABSENT, b"", ZERO_TIME_NS, b"")

    @staticmethod
    def new_for_block(signature: bytes, val_addr: bytes, ts_ns: int) -> "CommitSig":
        return CommitSig(BlockIDFlag.COMMIT, val_addr, ts_ns, signature)

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def absent(self) -> bool:
        return self.block_id_flag == BlockIDFlag.ABSENT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        if self.block_id_flag == BlockIDFlag.COMMIT:
            return commit_block_id
        if self.block_id_flag in (BlockIDFlag.ABSENT, BlockIDFlag.NIL):
            return BlockID()
        raise ValueError(f"Unknown BlockIDFlag: {self.block_id_flag}")

    def validate_basic(self) -> None:
        if self.block_id_flag not in (BlockIDFlag.ABSENT, BlockIDFlag.COMMIT, BlockIDFlag.NIL):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BlockIDFlag.ABSENT:
            if len(self.validator_address) != 0:
                raise ValueError("validator address is present")
            if self.timestamp_ns != ZERO_TIME_NS:
                raise ValueError("time is present")
            if len(self.signature) != 0:
                raise ValueError("signature is present")
        else:
            if len(self.validator_address) != crypto.ADDRESS_SIZE:
                raise ValueError(
                    f"expected ValidatorAddress size to be {crypto.ADDRESS_SIZE} bytes, "
                    f"got {len(self.validator_address)} bytes"
                )
            if len(self.signature) == 0:
                raise ValueError("signature is missing")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise ValueError(f"signature is too big (max: {MAX_SIGNATURE_SIZE})")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, int(self.block_id_flag))
        w.bytes(2, self.validator_address)
        w.message(3, pw.timestamp(self.timestamp_ns))
        w.bytes(4, self.signature)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "CommitSig":
        cs = CommitSig()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                cs.block_id_flag = BlockIDFlag(v)
            elif fn == 2:
                cs.validator_address = v
            elif fn == 3:
                cs.timestamp_ns = pw.parse_timestamp(v)
            elif fn == 4:
                cs.signature = v
        return cs


#: memo sentinel: vote_sign_bytes_columns legitimately caches None
_NO_COLUMNS = object()


@dataclass
class Commit:
    height: int
    round: int
    block_id: BlockID
    signatures: List[CommitSig] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def get_vote(self, val_idx: int) -> Vote:
        cs = self.signatures[val_idx]
        return Vote(
            type=SignedMsgType.PRECOMMIT,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp_ns=cs.timestamp_ns,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Canonical sign-bytes for validator val_idx's precommit (block.go:807)."""
        cs = self.signatures[val_idx]
        ts = cs.timestamp_ns
        if schemes.for_chain(chain_id).zero_precommit_ts:
            ts = schemes.AGG_ZERO_TS_NS
        return vote_sign_bytes(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            cs.block_id(self.block_id),
            ts,
        )

    def vote_sign_bytes_all(self, chain_id: str) -> List[bytes]:
        """Every validator's canonical sign-bytes in one pass, memoized per
        (chain_id, zero-ts flag). Batched commit verification needs all rows
        anyway, and the shared-field assembly
        (canonical.vote_sign_bytes_batch) plus the memo cut the dominant
        host-side cost of the device verify path. Commits are immutable once
        built, so the memo only invalidates if the chain's scheme flips
        zero_precommit_ts under us — hence the flag in the key."""
        zero = schemes.for_chain(chain_id).zero_precommit_ts
        cache = self.__dict__.setdefault("_sb_cache", {})
        hit = cache.get((chain_id, zero))
        if hit is None:
            hit = vote_sign_bytes_batch(
                chain_id,
                SignedMsgType.PRECOMMIT,
                self.height,
                self.round,
                [cs.block_id(self.block_id) for cs in self.signatures],
                [schemes.AGG_ZERO_TS_NS if zero else cs.timestamp_ns
                 for cs in self.signatures],
            )
            cache[(chain_id, zero)] = hit
        return hit

    def vote_sign_bytes_columns(self, chain_id: str):
        """Columnar sign-bytes (crypto.signcols.SignColumns) for the whole
        commit, memoized per (chain_id, scheme) like vote_sign_bytes_all — or
        None when the rows are not structurally uniform (nil votes mixed in,
        ragged timestamp encodings) or when the chain's scheme is not
        ed25519: the columns feed the ed25519 device pack path exclusively,
        and a memo keyed on chain_id alone would keep serving stale ed25519
        columns after the chain registers a different scheme. Row i
        reconstructs byte-identically to vote_sign_bytes_all(chain_id)[i]."""
        sch = schemes.for_chain(chain_id)
        if sch.scheme != schemes.SCHEME_ED25519:
            return None
        cache = self.__dict__.setdefault("_sbc_cache", {})
        key = (chain_id, sch.scheme, sch.zero_precommit_ts)
        hit = cache.get(key, _NO_COLUMNS)
        if hit is _NO_COLUMNS:
            hit = vote_sign_bytes_columns_batch(
                chain_id,
                SignedMsgType.PRECOMMIT,
                self.height,
                self.round,
                [cs.block_id(self.block_id) for cs in self.signatures],
                [cs.timestamp_ns for cs in self.signatures],
            )
            cache[key] = hit
        return hit

    def size(self) -> int:
        return len(self.signatures)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices([cs.encode() for cs in self.signatures])
        return self._hash

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if len(self.signatures) == 0:
                raise ValueError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.height)
        w.varint(2, self.round)
        w.message(3, self.block_id.encode())
        for cs in self.signatures:
            w.message(4, cs.encode())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Commit":
        """Polymorphic: the presence of the aggregate fields (5/6/7) makes
        the wire form self-describing, so every existing decode call site —
        block store, WAL, blocksync, light client — handles aggregated
        commits without knowing the chain's scheme."""
        height = round_ = 0
        block_id = BlockID()
        sigs: List[CommitSig] = []
        signers = None
        agg_sig = b""
        agg_ts = 0
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                height = pw.varint_to_int64(v)
            elif fn == 2:
                round_ = pw.varint_to_int64(v)
            elif fn == 3:
                block_id = BlockID.decode(v)
            elif fn == 4:
                sigs.append(CommitSig.decode(v))
            elif fn == 5:
                signers = BitArray.decode(v)
            elif fn == 6:
                agg_sig = v
            elif fn == 7:
                agg_ts = pw.varint_to_int64(v)
        if signers is not None or agg_sig:
            return AggregatedCommit(height, round_, block_id, [],
                                    signers=signers or BitArray(0),
                                    agg_sig=agg_sig, timestamp_ns=agg_ts)
        return Commit(height, round_, block_id, sigs)


@dataclass
class AggregatedCommit(Commit):
    """BLS fast-aggregate commit (the aggregated-commit block path; no
    reference equivalent).  Replaces the per-validator CommitSig list with
    one 48-byte aggregate signature over the shared zero-timestamp precommit
    sign-bytes, a signer bitmap positioned by validator index, and the
    voting-power-weighted median of the aggregated precommit timestamps.

    Wire form reuses Commit fields 1-3 and adds signers=5, agg_sig=6,
    timestamp=7; field 4 is never emitted, so Commit.decode dispatches on
    5/6 presence.  Verification is one fast-aggregate-verify against the
    apk of the bitmap's keys (validator_set.verify_commit*)."""

    signers: BitArray = field(default_factory=lambda: BitArray(0))
    agg_sig: bytes = b""
    timestamp_ns: int = 0

    def size(self) -> int:
        return self.signers.size()

    def signed(self, val_idx: int) -> bool:
        return self.signers.get_index(val_idx)

    def sign_message(self, chain_id: str) -> bytes:
        """The single canonical payload every signer in the bitmap signed
        (zero-timestamp precommit sign-bytes — see schemes.AGG_ZERO_TS_NS)."""
        return vote_sign_bytes(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            self.block_id,
            schemes.AGG_ZERO_TS_NS,
        )

    def get_vote(self, val_idx: int):
        raise TypeError("aggregated commit has no per-validator votes")

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        raise TypeError("aggregated commit has no per-validator sign-bytes")

    def vote_sign_bytes_all(self, chain_id: str):
        raise TypeError("aggregated commit has no per-validator sign-bytes")

    def vote_sign_bytes_columns(self, chain_id: str):
        return None

    def hash(self) -> bytes:
        if self._hash is None:
            w = pw.Writer()
            w.message(1, self.signers.encode())
            w.bytes(2, self.agg_sig)
            w.varint(3, self.timestamp_ns)
            self._hash = merkle.hash_from_byte_slices([w.finish()])
        return self._hash

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.signatures:
            raise ValueError("aggregated commit carries per-validator signatures")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if self.signers.size() == 0 or self.signers.num_true() == 0:
                raise ValueError("no signers in aggregated commit")
            from ..crypto.bls12381 import SIG_SIZE

            if len(self.agg_sig) != SIG_SIZE:
                raise ValueError(
                    f"aggregate signature must be {SIG_SIZE} bytes, "
                    f"got {len(self.agg_sig)}")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint(1, self.height)
        w.varint(2, self.round)
        w.message(3, self.block_id.encode())
        w.message(5, self.signers.encode())
        w.bytes(6, self.agg_sig)
        w.varint(7, self.timestamp_ns)
        return w.finish()


@dataclass
class Data:
    txs: List[bytes] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = txs_hash(self.txs)
        return self._hash

    def encode(self) -> bytes:
        w = pw.Writer()
        for tx in self.txs:
            w.bytes(1, tx) if tx else w.message(1, b"")
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Data":
        txs = [v for fn, _wt, v in pw.iter_fields(data) if fn == 1]
        return Data(txs=list(txs))


@dataclass
class Block:
    header: Header
    data: Data
    evidence: List = field(default_factory=list)  # List[Evidence]
    last_commit: Optional[Commit] = None

    def hash(self) -> Optional[bytes]:
        if self.last_commit is None and self.header.height > 1:
            return None
        self.fill_header()
        return self.header.hash()

    def fill_header(self) -> None:
        """Populate derived header hashes (block.go fillHeader)."""
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            from .evidence import evidence_list_hash

            self.header.evidence_hash = evidence_list_hash(self.evidence)

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.last_commit is None:
            if self.header.height > 1:
                raise ValueError("nil LastCommit")
        else:
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError(
                    f"wrong Header.LastCommitHash. Expected "
                    f"{self.last_commit.hash().hex().upper()}, got "
                    f"{self.header.last_commit_hash.hex().upper()}"
                )
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong Header.DataHash")
        from .evidence import evidence_list_hash

        if self.header.evidence_hash != evidence_list_hash(self.evidence):
            raise ValueError("wrong Header.EvidenceHash")

    def make_part_set(self, part_size: int = 65536):
        """Memoized: the sync/consensus paths build the part set of the same
        block several times (gossip entries, store save, proposal); encoding
        a 1000-signature block costs tens of ms, so rebuild only when asked
        for a different part size. Blocks are frozen once assembled (the
        memo key includes nothing mutable: fill_header() is idempotent)."""
        cached = self.__dict__.get("_part_set_cache")
        if cached is not None and cached[0] == part_size:
            return cached[1]
        from .part_set import PartSet

        self.fill_header()
        ps = PartSet.from_data(self.encode(), part_size)
        self.__dict__["_part_set_cache"] = (part_size, ps)
        return ps

    # -- proto (types/block.proto Block) ----------------------------------

    def encode(self) -> bytes:
        from .evidence import encode_evidence_list

        w = pw.Writer()
        w.message(1, self.header.encode())
        w.message(2, self.data.encode())
        w.message(3, encode_evidence_list(self.evidence))
        if self.last_commit is not None:
            w.message(4, self.last_commit.encode())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Block":
        from .evidence import decode_evidence_list

        header = Header()
        blk_data = Data()
        evidence: List = []
        last_commit = None
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                header = Header.decode(v)
            elif fn == 2:
                blk_data = Data.decode(v)
            elif fn == 3:
                evidence = decode_evidence_list(v)
            elif fn == 4:
                last_commit = Commit.decode(v)
        return Block(header, blk_data, evidence, last_commit)


@dataclass
class BlockMeta:
    """Stored per height in the block store (types/block_meta.go)."""

    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    def encode(self) -> bytes:
        w = pw.Writer()
        w.message(1, self.block_id.encode())
        w.varint(2, self.block_size)
        w.message(3, self.header.encode())
        w.varint(4, self.num_txs)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "BlockMeta":
        block_id = BlockID()
        header = Header()
        block_size = num_txs = 0
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                block_id = BlockID.decode(v)
            elif fn == 2:
                block_size = pw.varint_to_int64(v)
            elif fn == 3:
                header = Header.decode(v)
            elif fn == 4:
                num_txs = pw.varint_to_int64(v)
        return BlockMeta(block_id, block_size, header, num_txs)


def make_block(height: int, txs: List[bytes], last_commit: Optional[Commit],
               evidence: Optional[List] = None) -> Block:
    """Block skeleton; header chain fields are filled by state.MakeBlock."""
    return Block(
        header=Header(height=height),
        data=Data(txs=list(txs)),
        evidence=list(evidence or []),
        last_commit=last_commit,
    )
