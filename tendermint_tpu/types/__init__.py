"""Domain types (the reference's types/ tier, SURVEY.md §2.2).

Byte-identical wire artifacts: canonical sign-bytes, header/commit/validator-set
merkle hashes all match Tendermint v0.34.24 (reference types/canonical.go,
types/block.go:440, types/validator.go:117). Time is integer unix-nanoseconds
throughout (Go time.Time parity incl. the year-1 zero value).
"""

from .basic import (  # noqa: F401
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    ZERO_TIME_NS,
)
from .validator import Validator, new_validator  # noqa: F401
from .validator_set import ValidatorSet  # noqa: F401
from .vote import Vote  # noqa: F401
from .block import Block, Commit, CommitSig, Data, Header  # noqa: F401
from .proposal import Proposal  # noqa: F401
from .part_set import Part, PartSet  # noqa: F401
from .vote_set import VoteSet  # noqa: F401
from .params import ConsensusParams, default_consensus_params  # noqa: F401
from .evidence import (  # noqa: F401
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
)
from .genesis import GenesisDoc, GenesisValidator  # noqa: F401
from .priv_validator import MockPV, PrivValidator  # noqa: F401
from .errors import (  # noqa: F401
    ErrInvalidCommitHeight,
    ErrInvalidCommitSignatures,
    ErrNotEnoughVotingPowerSigned,
    ErrVoteInvalidSignature,
)
