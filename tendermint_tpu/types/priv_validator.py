"""PrivValidator interface + MockPV (reference types/priv_validator.go:15).

The file-backed FilePV with double-sign protection lives in
tendermint_tpu/privval (reference privval/file.go).
"""

from __future__ import annotations

from .. import crypto
from .proposal import Proposal
from .vote import Vote


class PrivValidator:
    def get_pub_key(self) -> crypto.PubKey:
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Sets vote.signature in place (as the reference mutates the proto)."""
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        raise NotImplementedError


class MockPV(PrivValidator):
    """In-memory signer for tests (types/priv_validator.go MockPV)."""

    def __init__(self, priv_key: "crypto.PrivKey | None" = None,
                 break_proposal_sigs: bool = False, break_vote_sigs: bool = False):
        self.priv_key = priv_key or crypto.Ed25519PrivKey.generate()
        self.break_proposal_sigs = break_proposal_sigs
        self.break_vote_sigs = break_vote_sigs

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        use_chain_id = "incorrect-chain-id" if self.break_vote_sigs else chain_id
        vote.signature = self.priv_key.sign(vote.sign_bytes(use_chain_id))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        use_chain_id = "incorrect-chain-id" if self.break_proposal_sigs else chain_id
        proposal.signature = self.priv_key.sign(proposal.sign_bytes(use_chain_id))
