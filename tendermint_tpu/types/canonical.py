"""Canonical sign-bytes (reference types/canonical.go + proto canonical.pb.go).

These are the exact bytes validators sign and verifiers check — the payload of
the TPU batch-verify hot path. Encoding quirks that matter (verified against
canonical.pb.go:517-567):

* height/round are sfixed64 little-endian, omitted when zero;
* the Timestamp field is non-nullable: ALWAYS emitted, even for zero time;
* CanonicalBlockID is a nullable pointer: omitted for nil/zero block ids;
* inside CanonicalBlockID the part_set_header is non-nullable: always emitted;
* the whole message is varint length-prefixed (libs/protoio MarshalDelimited).
"""

from __future__ import annotations

from ..libs import protowire as pw
from .basic import BlockID, SignedMsgType


def canonical_block_id_bytes(block_id: BlockID) -> "bytes | None":
    if block_id.is_zero():
        return None
    w = pw.Writer()
    w.bytes(1, block_id.hash)
    w.message(2, block_id.part_set_header.encode())
    return w.finish()


def vote_sign_bytes(
    chain_id: str,
    vote_type: SignedMsgType,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalVote, length-delimited (types/vote.go:93 VoteSignBytes)."""
    w = pw.Writer()
    w.varint(1, int(vote_type))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message_opt(4, canonical_block_id_bytes(block_id))
    w.message(5, pw.timestamp(timestamp_ns))
    w.string(6, chain_id)
    return pw.length_delimited(w.finish())


def vote_sign_bytes_batch(
    chain_id: str,
    vote_type: SignedMsgType,
    height: int,
    round_: int,
    block_ids,
    timestamps_ns,
) -> "list[bytes]":
    """Batched :func:`vote_sign_bytes` over one commit's rows.

    A commit's sign-bytes share every field except the timestamp message and
    (for nil votes) the block id, so the shared fields are encoded once and
    each row is assembled from cached pieces — ~6x faster than per-index
    encoding at 1000 validators, which matters because sign-bytes
    construction is the host-side cost floor of the batched verify path.
    Byte-identical to vote_sign_bytes (differentially tested)."""
    w = pw.Writer()
    w.varint(1, int(vote_type))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    prefix = w.finish()
    sw = pw.Writer()
    sw.string(6, chain_id)
    suffix = sw.finish()
    ev = pw.encode_varint
    f4_cache: dict = {}
    sec_cache: dict = {}
    tail_len = len(prefix) + len(suffix)
    out = []
    for bid, ns in zip(block_ids, timestamps_ns):
        f4 = f4_cache.get(bid)
        if f4 is None:
            body = canonical_block_id_bytes(bid)
            # field 4, wire type 2 -> tag byte 0x22; omitted for zero ids
            f4 = b"" if body is None else b"\x22" + ev(len(body)) + body
            f4_cache[bid] = f4
        # Timestamp body inlined (== pw.timestamp): a commit's rows share
        # the seconds value, so its varint is cached; nanos is per-row
        seconds, nanos = divmod(ns, 1_000_000_000)
        ts = sec_cache.get(seconds)
        if ts is None:
            ts = b"\x08" + ev(seconds) if seconds else b""  # ts field 1
            sec_cache[seconds] = ts
        if nanos:
            ts = ts + b"\x10" + ev(nanos)  # ts field 2
        f5 = b"\x2a" + ev(len(ts)) + ts  # field 5, wire type 2
        body_len = tail_len + len(f4) + len(f5)
        out.append(ev(body_len) + prefix + f4 + f5 + suffix)
    return out


def vote_sign_bytes_columns_batch(
    chain_id: str,
    vote_type: SignedMsgType,
    height: int,
    round_: int,
    block_ids,
    timestamps_ns,
):
    """Columnar form of :func:`vote_sign_bytes_batch`: a SignColumns
    (template + varying byte positions + per-row values) built straight
    from the encoder's cached fragments, or ``None`` when the rows are not
    structurally uniform (mixed block ids — nil votes — or timestamp
    encodings of different byte lengths, where rows shift relative to each
    other and a shared template does not exist).

    The point is what it does NOT do: no per-row bytes objects, no
    O(n*mlen) join + diff scan downstream — the device pack path
    (prepare_sparse_stream) consumes the arrays directly. Row
    reconstruction is byte-identical to vote_sign_bytes_batch
    (differential tests in tests/test_multidevice_stream.py)."""
    import numpy as np

    from ..crypto.signcols import SignColumns

    n = len(timestamps_ns)
    if n == 0:
        return None
    first_bid = block_ids[0]
    for bid in block_ids:
        if bid != first_bid:
            return None  # nil rows mix in: f4 omitted, rows shift
    w = pw.Writer()
    w.varint(1, int(vote_type))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    prefix = w.finish()
    body = canonical_block_id_bytes(first_bid)
    ev = pw.encode_varint
    f4 = b"" if body is None else b"\x22" + ev(len(body)) + body
    sw = pw.Writer()
    sw.string(6, chain_id)
    suffix = sw.finish()

    # per-row timestamp field 5 (same fragment layout as
    # vote_sign_bytes_batch: cached seconds varint + per-row nanos)
    sec_cache: dict = {}
    frags = []
    flen = None
    for ns in timestamps_ns:
        seconds, nanos = divmod(ns, 1_000_000_000)
        ts = sec_cache.get(seconds)
        if ts is None:
            ts = b"\x08" + ev(seconds) if seconds else b""
            sec_cache[seconds] = ts
        if nanos:
            ts = ts + b"\x10" + ev(nanos)
        f5 = b"\x2a" + ev(len(ts)) + ts
        if flen is None:
            flen = len(f5)
        elif len(f5) != flen:
            return None  # ragged timestamps: no shared template
        frags.append(f5)

    body_len = len(prefix) + len(f4) + flen + len(suffix)
    head = ev(body_len) + prefix + f4
    template = np.frombuffer(head + frags[0] + suffix, dtype=np.uint8)
    frag_arr = np.frombuffer(b"".join(frags), dtype=np.uint8).reshape(n, flen)
    diff = (frag_arr != frag_arr[0]).any(axis=0)
    cols = (np.nonzero(diff)[0] + len(head)).astype(np.int32)
    return SignColumns(template, cols, frag_arr[:, diff])


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal, length-delimited (types/proposal.go ProposalSignBytes)."""
    w = pw.Writer()
    w.varint(1, int(SignedMsgType.PROPOSAL))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.varint(4, pol_round)  # int64 varint (canonical.proto:25)
    w.message_opt(5, canonical_block_id_bytes(block_id))
    w.message(6, pw.timestamp(timestamp_ns))
    w.string(7, chain_id)
    return pw.length_delimited(w.finish())
