"""Canonical sign-bytes (reference types/canonical.go + proto canonical.pb.go).

These are the exact bytes validators sign and verifiers check — the payload of
the TPU batch-verify hot path. Encoding quirks that matter (verified against
canonical.pb.go:517-567):

* height/round are sfixed64 little-endian, omitted when zero;
* the Timestamp field is non-nullable: ALWAYS emitted, even for zero time;
* CanonicalBlockID is a nullable pointer: omitted for nil/zero block ids;
* inside CanonicalBlockID the part_set_header is non-nullable: always emitted;
* the whole message is varint length-prefixed (libs/protoio MarshalDelimited).
"""

from __future__ import annotations

from ..libs import protowire as pw
from .basic import BlockID, SignedMsgType


def canonical_block_id_bytes(block_id: BlockID) -> "bytes | None":
    if block_id.is_zero():
        return None
    w = pw.Writer()
    w.bytes(1, block_id.hash)
    w.message(2, block_id.part_set_header.encode())
    return w.finish()


def vote_sign_bytes(
    chain_id: str,
    vote_type: SignedMsgType,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalVote, length-delimited (types/vote.go:93 VoteSignBytes)."""
    w = pw.Writer()
    w.varint(1, int(vote_type))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message_opt(4, canonical_block_id_bytes(block_id))
    w.message(5, pw.timestamp(timestamp_ns))
    w.string(6, chain_id)
    return pw.length_delimited(w.finish())


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal, length-delimited (types/proposal.go ProposalSignBytes)."""
    w = pw.Writer()
    w.varint(1, int(SignedMsgType.PROPOSAL))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.varint(4, pol_round)  # int64 varint (canonical.proto:25)
    w.message_opt(5, canonical_block_id_bytes(block_id))
    w.message(6, pw.timestamp(timestamp_ns))
    w.string(7, chain_id)
    return pw.length_delimited(w.finish())
