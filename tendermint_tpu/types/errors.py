"""Typed errors for commit/vote verification (reference types/errors.go, types/vote.go)."""

from __future__ import annotations


class TypesError(Exception):
    pass


class ErrInvalidCommitHeight(TypesError):
    def __init__(self, expected: int, actual: int):
        super().__init__(f"invalid commit -- wrong height: {expected} vs {actual}")
        self.expected = expected
        self.actual = actual


class ErrInvalidCommitSignatures(TypesError):
    def __init__(self, expected: int, actual: int):
        super().__init__(f"invalid commit -- wrong set size: {expected} vs {actual}")
        self.expected = expected
        self.actual = actual


class ErrNotEnoughVotingPowerSigned(TypesError):
    def __init__(self, got: int, needed: int):
        super().__init__(f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}")
        self.got = got
        self.needed = needed


class ErrWrongSignature(TypesError):
    def __init__(self, idx: int, sig: bytes):
        super().__init__(f"wrong signature (#{idx}): {sig.hex().upper()}")
        self.idx = idx


class ErrVoteInvalidSignature(TypesError):
    def __init__(self):
        super().__init__("invalid signature")


class ErrVoteInvalidValidatorAddress(TypesError):
    def __init__(self):
        super().__init__("invalid validator address")


class ErrVoteNonDeterministicSignature(TypesError):
    pass


class ErrVoteConflictingVotes(TypesError):
    def __init__(self, vote_a, vote_b):
        super().__init__("conflicting votes from validator")
        self.vote_a = vote_a
        self.vote_b = vote_b
