"""ValidatorSet: sorted set, deterministic proposer rotation, commit verification.

Semantics mirror reference types/validator_set.go exactly (int64 clipping,
priority rescale/center, update/removal merge order, error precedence in the
three VerifyCommit variants at :667/:722/:775). The difference is HOW commits
are verified: all candidate signatures are collected into one BatchVerifier
call (TPU Pallas kernel batch) and the scalar loop's decisions — including
VerifyCommitLight's early exit at 2/3 — are replayed over the batch verdicts,
so accept/reject and error selection are byte-identical to the reference while
the crypto runs as one device batch instead of N host calls.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from ..crypto.batch import BatchVerifier
from .basic import BlockID, BlockIDFlag
from .errors import (
    ErrInvalidCommitHeight,
    ErrInvalidCommitSignatures,
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
)
from .validator import (
    MAX_TOTAL_VOTING_POWER,
    PRIORITY_WINDOW_SIZE_FACTOR,
    Validator,
    safe_add_clip,
    safe_mul,
    safe_sub_clip,
)

# Fraction as (numerator, denominator) — reference libs/math.Fraction.
Fraction = Tuple[int, int]


def _is_aggregated(commit) -> bool:
    """Duck-typed (types.block.AggregatedCommit carries agg_sig/signers) so
    this module need not import types.block."""
    return hasattr(commit, "agg_sig")


def _observe_aggregated_wire_size(commit) -> None:
    """Feed the verified commit's encoded size into the aggregated-commit
    wire-size histogram (telemetry only; never affects the verdict)."""
    from ..crypto import phases as _phases

    m = _phases.metrics
    if m is None:
        return
    try:
        m.aggregated_commit_bytes.observe(float(len(commit.encode())))
    except Exception:
        pass


def _by_voting_power(v: Validator):
    """Sort key: power desc, address asc (reference types/validator.go ValidatorsByVotingPower)."""
    return (-v.voting_power, v.address)


class ValidatorSet:
    def __init__(self, validators: Optional[Sequence[Validator]] = None):
        """NewValidatorSet semantics (validator_set.go:70): copies, validates,
        sorts, and runs one IncrementProposerPriority(1)."""
        self.validators: List[Validator] = []
        self.proposer: Optional[Validator] = None
        self._total_voting_power: Optional[int] = None
        # structural-mutation counter: every mutator that changes membership
        # or ORDER bumps it, so the _addr_index/hash memos below cannot go
        # stale even for an in-place mutation that preserves the list
        # object's identity and length (advisor finding at _addr_index)
        self._mutations = 0
        if validators is not None:
            self._update_with_change_set([v.copy() for v in validators], allow_deletes=False)
            if len(self.validators) > 0:
                self.increment_proposer_priority(1)

    @classmethod
    def from_existing(cls, validators: Sequence[Validator]) -> "ValidatorSet":
        """(validator_set.go ValidatorSetFromExistingValidators) rebuild a
        set whose proposer priorities are ALREADY live — RPC /validators
        answers, statesync bootstrap — without NewValidatorSet's extra
        IncrementProposerPriority(1). The proposer is recovered from the
        existing priorities; re-incrementing here desynchronizes proposer
        selection from the running network (found by the statesync e2e
        manifest: the synced node rejected every proposal)."""
        vs = cls()
        vs.validators = sorted((v.copy() for v in validators),
                               key=_by_voting_power)
        vs._bump_mutations()
        if vs.validators:
            # findPreviousProposer (validator_set.go:832): the chosen
            # proposer was decremented by the total power, so it is the one
            # that LOSES the priority comparison against every other
            prev = None
            for v in vs.validators:
                if prev is None:
                    prev = v
                elif prev is prev.compare_proposer_priority(v):
                    prev = v
            vs.proposer = prev
        return vs

    # -- basic accessors ---------------------------------------------------

    def __len__(self) -> int:
        return len(self.validators)

    def size(self) -> int:
        return len(self.validators)

    def is_nil_or_empty(self) -> bool:
        return len(self.validators) == 0

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet()
        vs.validators = [v.copy() for v in self.validators]
        vs.proposer = self.proposer
        vs._total_voting_power = self._total_voting_power
        # membership and powers are identical, so the merkle hash carries
        # over (priorities are not part of bytes_for_hash); re-keyed to the
        # copy's own list + mutation count so later structural mutations
        # invalidate normally
        cache = self.__dict__.get("_hash_cache")
        if cache is not None and cache[0] is self.validators \
                and cache[1] == self._mutations \
                and cache[2] == len(self.validators):
            vs.__dict__["_hash_cache"] = (vs.validators, vs._mutations,
                                          len(vs.validators), cache[3])
        return vs

    def _bump_mutations(self) -> None:
        """Every structural mutator (membership OR order change) must call
        this; the _addr_index/hash memos key on the counter, so an in-place
        mutation that preserves list identity and length still invalidates."""
        self._mutations += 1

    def _addr_index(self) -> dict:
        """address -> index, rebuilt whenever the validators list object is
        replaced, resized, or a structural mutator bumps ``_mutations``
        (priority updates mutate Validator objects but never addresses or
        order, so the cache stays valid across IncrementProposerPriority).
        At light-client/commit-verification scale the linear scan was the
        single hottest host-side cost (1000-validator sets x 32k lookups)."""
        cache = self.__dict__.get("_addr_cache")
        if (cache is None or cache[0] is not self.validators
                or cache[1] != self._mutations
                or cache[2] != len(self.validators)):
            idx: dict = {}
            for i, v in enumerate(self.validators):
                idx.setdefault(v.address, i)  # first match wins, like the scan
            cache = (self.validators, self._mutations, len(self.validators),
                     idx)
            self.__dict__["_addr_cache"] = cache
        return cache[3]

    def has_address(self, address: bytes) -> bool:
        return address in self._addr_index()

    def get_by_address(self, address: bytes) -> Tuple[int, Optional[Validator]]:
        i = self._addr_index().get(address)
        if i is None:
            return -1, None
        return i, self.validators[i].copy()

    def get_by_index(self, index: int) -> Tuple[bytes, Optional[Validator]]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v.copy()

    def total_voting_power(self) -> int:
        if self._total_voting_power is None:
            self._update_total_voting_power()
        return self._total_voting_power

    def _update_total_voting_power(self) -> None:
        total = 0
        for v in self.validators:
            total = safe_add_clip(total, v.voting_power)
            if total > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    f"total voting power cannot be guarded to not exceed {MAX_TOTAL_VOTING_POWER}; got: {total}"
                )
        self._total_voting_power = total

    def hash(self) -> bytes:
        """Merkle root of SimpleValidator encodings (validator_set.go:347).

        Memoized under the same invalidation contract as _addr_index (list
        identity + length + the structural mutation counter): priority
        rotation — the only in-place mutation that doesn't bump the counter
        — does not touch bytes_for_hash. validate_block hashes two
        1000-validator sets per block, and copy() propagates the memo, so
        steady-state fast sync pays the merkle pass only when membership
        actually changes."""
        cache = self.__dict__.get("_hash_cache")
        if (cache is None or cache[0] is not self.validators
                or cache[1] != self._mutations
                or cache[2] != len(self.validators)):
            from ..crypto import merkle

            h = merkle.hash_from_byte_slices(
                [v.bytes_for_hash() for v in self.validators])
            cache = (self.validators, self._mutations, len(self.validators), h)
            self.__dict__["_hash_cache"] = cache
        return cache[3]

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for idx, v in enumerate(self.validators):
            try:
                v.validate_basic()
            except ValueError as e:
                raise ValueError(f"invalid validator #{idx}: {e}")
        if self.proposer is None:
            raise ValueError("proposer failed validate basic, error: nil validator")
        self.proposer.validate_basic()

    # -- proposer rotation (validator_set.go:107-256) ----------------------

    def get_proposer(self) -> Optional[Validator]:
        if len(self.validators) == 0:
            return None
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        proposer = None
        for v in self.validators:
            proposer = v if proposer is None else proposer.compare_proposer_priority(v)
        return proposer

    def increment_proposer_priority(self, times: int) -> None:
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("cannot call IncrementProposerPriority with non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = safe_add_clip(v.proposer_priority, v.voting_power)
        mostest = self._find_proposer()
        mostest.proposer_priority = safe_sub_clip(mostest.proposer_priority, self.total_voting_power())
        return mostest

    def rescale_priorities(self, diff_max: int) -> None:
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if diff_max <= 0:
            return
        diff = self._max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                # Go int division truncates toward zero; Python floors.
                p = v.proposer_priority
                v.proposer_priority = -((-p) // ratio) if p < 0 else p // ratio

    def _max_min_priority_diff(self) -> int:
        mx = max(v.proposer_priority for v in self.validators)
        mn = min(v.proposer_priority for v in self.validators)
        return abs(mx - mn)

    def _compute_avg_proposer_priority(self) -> int:
        n = len(self.validators)
        s = sum(v.proposer_priority for v in self.validators)
        # Go big.Int Div floors (Euclidean for positive divisor) — matches //.
        return s // n

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = safe_sub_clip(v.proposer_priority, avg)

    # -- updates (validator_set.go:371-665) --------------------------------

    def update_with_change_set(self, changes: Sequence[Validator]) -> None:
        self._update_with_change_set([c.copy() for c in changes], allow_deletes=True)

    def _update_with_change_set(self, changes: List[Validator], allow_deletes: bool) -> None:
        if len(changes) == 0:
            return
        updates, deletes = _process_changes(changes)
        if not allow_deletes and deletes:
            raise ValueError(f"cannot process validators with voting power 0: {deletes}")
        num_new = sum(1 for u in updates if not self.has_address(u.address))
        if num_new == 0 and len(self.validators) == len(deletes):
            raise ValueError("applying the validator changes would result in empty set")
        removed_power = self._verify_removals(deletes)
        tvp_after_updates = self._verify_updates(updates, removed_power)
        self._compute_new_priorities(updates, tvp_after_updates)
        self._apply_updates(updates)
        self._apply_removals(deletes)
        self._total_voting_power = None
        self._update_total_voting_power()
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        # reassign (not in-place sort) AND bump: either alone invalidates
        # the _addr_index/hash memos; both keeps the invariant obvious
        self.validators = sorted(self.validators, key=_by_voting_power)
        self._bump_mutations()

    def _verify_removals(self, deletes: List[Validator]) -> int:
        removed = 0
        for d in deletes:
            _, val = self.get_by_address(d.address)
            if val is None:
                raise ValueError(f"failed to find validator {d.address.hex().upper()} to remove")
            removed += val.voting_power
        if len(deletes) > len(self.validators):
            raise ValueError("more deletes than validators")
        return removed

    def _verify_updates(self, updates: List[Validator], removed_power: int) -> int:
        def delta(u: Validator) -> int:
            _, val = self.get_by_address(u.address)
            return u.voting_power - val.voting_power if val is not None else u.voting_power

        ordered = sorted(updates, key=delta)
        tvp_after_removals = self.total_voting_power() - removed_power
        for u in ordered:
            tvp_after_removals += delta(u)
            if tvp_after_removals > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    f"total voting power of resulting valset exceeds max {MAX_TOTAL_VOTING_POWER}"
                )
        return tvp_after_removals + removed_power

    def _compute_new_priorities(self, updates: List[Validator], updated_tvp: int) -> None:
        for u in updates:
            _, val = self.get_by_address(u.address)
            if val is None:
                # -1.125*totalVotingPower so rejoining validators can't reset
                # their priority (validator_set.go:483-490).
                u.proposer_priority = -(updated_tvp + (updated_tvp >> 3))
            else:
                u.proposer_priority = val.proposer_priority

    def _apply_updates(self, updates: List[Validator]) -> None:
        existing = sorted(self.validators, key=lambda v: v.address)
        merged: List[Validator] = []
        i = j = 0
        while i < len(existing) and j < len(updates):
            if existing[i].address < updates[j].address:
                merged.append(existing[i])
                i += 1
            else:
                merged.append(updates[j])
                if existing[i].address == updates[j].address:
                    i += 1
                j += 1
        merged.extend(existing[i:])
        merged.extend(updates[j:])
        self.validators = merged

    def _apply_removals(self, deletes: List[Validator]) -> None:
        if not deletes:
            return
        dset = {d.address for d in deletes}
        self.validators = [v for v in self.validators if v.address not in dset]

    # -- commit verification (validator_set.go:667-821) --------------------
    #
    # Each variant: one batched device call over the candidate signatures,
    # then a sequential replay of the reference's scalar loop over the
    # verdicts so error precedence and early exits match exactly.

    def verify_commit(self, chain_id: str, block_id: BlockID, height: int, commit) -> None:
        """All signatures checked; absent skipped; nil votes verified but not
        tallied (validator_set.go:667)."""
        self._check_commit_shape(commit, height, block_id)
        if _is_aggregated(commit):
            return self._verify_aggregated(chain_id, commit, mode="full")
        idxs = [i for i, cs in enumerate(commit.signatures) if not cs.absent()]
        ok = self._batch_verify(chain_id, commit, idxs)
        tallied = 0
        needed = self.total_voting_power() * 2 // 3
        for pos, idx in enumerate(idxs):
            cs = commit.signatures[idx]
            if not ok[pos]:
                raise ErrWrongSignature(idx, cs.signature)
            if cs.for_block():
                tallied += self.validators[idx].voting_power
        if tallied <= needed:
            raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    def verify_commit_light(self, chain_id: str, block_id: BlockID, height: int, commit) -> None:
        """Stops at 2/3: signatures after the early-exit point are never
        examined (validator_set.go:722) — the replay preserves that."""
        self._check_commit_shape(commit, height, block_id)
        if _is_aggregated(commit):
            # one pairing over the whole bitmap: there is no cheaper
            # early-exit prefix to stop at
            return self._verify_aggregated(chain_id, commit, mode="light")
        idxs = [i for i, cs in enumerate(commit.signatures) if cs.for_block()]
        ok = self._batch_verify(chain_id, commit, idxs, plane="light")
        tallied = 0
        needed = self.total_voting_power() * 2 // 3
        for pos, idx in enumerate(idxs):
            if not ok[pos]:
                raise ErrWrongSignature(idx, commit.signatures[idx].signature)
            tallied += self.validators[idx].voting_power
            if tallied > needed:
                return
        raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    def verify_commit_light_trusting(self, chain_id: str, commit,
                                     trust_level: Fraction,
                                     commit_vals: "ValidatorSet" = None) -> None:
        """Address-lookup variant over a *trusted* set (validator_set.go:775).

        `commit_vals` is only consulted for aggregated commits: the aggregate
        signature covers every key in the signer bitmap — positioned by index
        into the COMMIT's validator set, which the trusted set (self) may not
        contain — so the pairing needs the commit-height set while the
        trust-level tally intersects the bitmap with self."""
        numer, denom = trust_level
        if denom == 0:
            raise ValueError("trustLevel has zero Denominator")
        total_mul, overflow = safe_mul(self.total_voting_power(), numer)
        if overflow:
            raise OverflowError(
                "int64 overflow while calculating voting power needed. "
                "please provide smaller trustLevel numerator"
            )
        needed = total_mul // denom

        if _is_aggregated(commit):
            return self._verify_aggregated_trusting(
                chain_id, commit, needed, commit_vals)

        # Candidates: for-block sigs whose address is in the trusted set.
        cand: List[Tuple[int, int, Validator]] = []  # (commit idx, val idx, val)
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val_idx, val = self.get_by_address(cs.validator_address)
            if val is not None:
                cand.append((idx, val_idx, val))
        ok = self._batch_verify(chain_id, commit, [c[0] for c in cand],
                                pubkeys=[c[2].pub_key for c in cand],
                                plane="light")
        tallied = 0
        seen = {}
        for pos, (idx, val_idx, val) in enumerate(cand):
            if val_idx in seen:
                raise ValueError(f"double vote from {val}: ({seen[val_idx]} and {idx})")
            seen[val_idx] = idx
            if not ok[pos]:
                raise ErrWrongSignature(idx, commit.signatures[idx].signature)
            tallied += val.voting_power
            if tallied > needed:
                return
        raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    def _check_commit_shape(self, commit, height: int, block_id: BlockID) -> None:
        # commit.size(): CommitSig rows for plain commits, signer-bitmap
        # length for aggregated ones — both must equal the set size
        if self.size() != commit.size():
            raise ErrInvalidCommitSignatures(self.size(), commit.size())
        if height != commit.height:
            raise ErrInvalidCommitHeight(height, commit.height)
        if block_id != commit.block_id:
            raise ValueError(
                f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
            )

    def _verify_aggregated(self, chain_id: str, commit,
                           mode: str = "full") -> None:
        """One fast-aggregate-verify replaces the per-signature batch: apk
        over the bitmap's pubkeys, pairing against the shared zero-timestamp
        sign-bytes. Error precedence mirrors the scalar replay — shape
        (caller), then signature (ErrWrongSignature), then the 2/3 tally
        (ErrNotEnoughVotingPowerSigned)."""
        from ..crypto.bls12381.vec import fast_aggregate_verify_routed

        _observe_aggregated_wire_size(commit)
        signer_idxs = commit.signers.true_indices()
        pks = [self.validators[i].pub_key.bytes() for i in signer_idxs]
        msg = commit.sign_message(chain_id)
        if not fast_aggregate_verify_routed(pks, msg, commit.agg_sig,
                                            mode=mode):
            raise ErrWrongSignature(-1, commit.agg_sig)
        tallied = sum(self.validators[i].voting_power for i in signer_idxs)
        needed = self.total_voting_power() * 2 // 3
        if tallied <= needed:
            raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    def _verify_aggregated_trusting(self, chain_id: str, commit, needed: int,
                                    commit_vals: "ValidatorSet") -> None:
        """Trusting-mode aggregate check: the pairing must run over the FULL
        bitmap (the aggregate covers every signer), keyed by the commit
        validator set; only the trusted intersection tallies toward the
        trust level."""
        from ..crypto.bls12381.vec import fast_aggregate_verify_routed

        if commit_vals is None:
            # self must BE the commit-height set then (e.g. evidence checks
            # against the recorded set); a size mismatch means it is not
            commit_vals = self
        if commit_vals.size() != commit.size():
            raise ErrInvalidCommitSignatures(commit_vals.size(), commit.size())
        _observe_aggregated_wire_size(commit)
        signer_idxs = commit.signers.true_indices()
        pks = [commit_vals.validators[i].pub_key.bytes() for i in signer_idxs]
        msg = commit.sign_message(chain_id)
        if not fast_aggregate_verify_routed(pks, msg, commit.agg_sig,
                                            mode="trusting"):
            raise ErrWrongSignature(-1, commit.agg_sig)
        addr_idx = self._addr_index()
        tallied = 0
        for i in signer_idxs:
            val_idx = addr_idx.get(commit_vals.validators[i].address)
            if val_idx is None:
                continue
            tallied += self.validators[val_idx].voting_power
            if tallied > needed:
                return
        raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    def _batch_verify(self, chain_id: str, commit, idxs: Sequence[int],
                      pubkeys: Optional[Sequence] = None,
                      plane: str = "votes") -> List[bool]:
        if not idxs:
            return []
        bv = BatchVerifier(plane=plane)
        # amortized sign-bytes: one shared-field encode for the whole commit
        # instead of len(idxs) canonical encodes (the host-side cost floor)
        sb = (commit.vote_sign_bytes_all(chain_id) if len(idxs) > 32
              else None)
        for pos, idx in enumerate(idxs):
            pk = pubkeys[pos] if pubkeys is not None else self.validators[idx].pub_key
            msg = sb[idx] if sb is not None else commit.vote_sign_bytes(chain_id, idx)
            bv.add(pk, msg, commit.signatures[idx].signature)
        if sb is not None:
            # columnar fast path: hand the device packer the commit's
            # sign-bytes structure (template + varying timestamp columns)
            # so it skips the per-segment join + diff re-discovery. None
            # for structurally non-uniform commits (nil votes mixed in).
            cols = commit.vote_sign_bytes_columns(chain_id)
            if cols is not None:
                bv.set_columns(cols.subset(idxs))
        _, per_item = bv.verify()
        return [bool(b) for b in per_item]

    # -- proto ------------------------------------------------------------

    def encode(self) -> bytes:
        from ..libs import protowire as pw

        w = pw.Writer()
        for v in self.validators:
            w.message(1, v.encode())
        if self.proposer is not None:
            w.message(2, self.proposer.encode())
        w.varint(3, self.total_voting_power())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "ValidatorSet":
        from ..libs import protowire as pw

        vs = ValidatorSet()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                vs.validators.append(Validator.decode(v))
            elif fn == 2:
                vs.proposer = Validator.decode(v)
        vs._total_voting_power = None
        vs._bump_mutations()
        return vs


def verify_commit_light_batched(
    entries: Sequence[Tuple["ValidatorSet", str, BlockID, int, object]],
) -> List[Optional[Exception]]:
    """Window-batched VerifyCommitLight: many (valset, commit) pairs, ONE
    device call.

    The fast-sync replay path (reference blockchain/v0/reactor.go:255 verifies
    one commit per loop iteration) is the TPU batch opportunity: all candidate
    signatures across a window of contiguous blocks go to the device together,
    then each commit's scalar precedence loop — including the 2/3 early exit —
    is replayed over its verdict slice. Per-entry outcome is None (ok) or the
    exact exception verify_commit_light would have raised.

    Entries: (val_set, chain_id, block_id, height, commit).
    """
    bv = BatchVerifier(plane="light")
    slices: List[Tuple[int, List[int]]] = []  # (batch offset, candidate idxs)
    shape_errors: List[Optional[Exception]] = []
    agg_done: dict = {}  # entry position -> result for aggregated commits
    off = 0
    for pos_e, (val_set, chain_id, block_id, height, commit) in enumerate(entries):
        if _is_aggregated(commit):
            # already one pairing per commit — nothing to fold into the
            # ed25519 batch; verify inline and record the outcome
            try:
                val_set.verify_commit_light(chain_id, block_id, height, commit)
                agg_done[pos_e] = None
            except Exception as e:
                agg_done[pos_e] = e
            shape_errors.append(None)
            slices.append((off, []))
            continue
        try:
            val_set._check_commit_shape(commit, height, block_id)
        except Exception as e:  # shape errors surface per-entry, not batch-wide
            shape_errors.append(e)
            slices.append((off, []))
            continue
        shape_errors.append(None)
        idxs = [i for i, cs in enumerate(commit.signatures) if cs.for_block()]
        sb = commit.vote_sign_bytes_all(chain_id)
        vals = val_set.validators
        for idx in idxs:
            bv.add(vals[idx].pub_key, sb[idx], commit.signatures[idx].signature)
        slices.append((off, idxs))
        off += len(idxs)
    _, per_item = bv.verify()

    results: List[Optional[Exception]] = []
    for pos_e, (entry, shape_err, (start, idxs)) in enumerate(
            zip(entries, shape_errors, slices)):
        if pos_e in agg_done:
            results.append(agg_done[pos_e])
            continue
        if shape_err is not None:
            results.append(shape_err)
            continue
        val_set, chain_id, block_id, height, commit = entry
        tallied = 0
        needed = val_set.total_voting_power() * 2 // 3
        err: Optional[Exception] = None
        for pos, idx in enumerate(idxs):
            if not per_item[start + pos]:
                err = ErrWrongSignature(idx, commit.signatures[idx].signature)
                break
            tallied += val_set.validators[idx].voting_power
            if tallied > needed:
                break
        else:
            err = ErrNotEnoughVotingPowerSigned(tallied, needed)
        results.append(err)
    return results


def verify_commit_light_trusting_batched(
    entries: Sequence[Tuple["ValidatorSet", str, object, "Fraction"]],
) -> List[Optional[Exception]]:
    """Window-batched VerifyCommitLightTrusting: the light client's bisection
    walk verifies a chain of headers against a *trusted* set
    (validator_set.go:775, light/verifier.go:32) — all candidate signatures
    across the window ride one batched device call, then each commit's
    scalar precedence loop (address lookup, duplicate-vote check, trust-level
    tally with early exit) replays over its verdict slice.

    Entries: (trusted_val_set, chain_id, commit, trust_level) or, for
    aggregated commits crossing a valset change, the 5-tuple
    (..., commit_vals) carrying the commit-height validator set — the
    bitmap indexes into THAT set, so the pairing needs it whenever it
    differs from the trusted set (mirrors light/verifier.py
    verify_non_adjacent).  Per-entry outcome is None (ok) or the exact
    exception verify_commit_light_trusting would have raised.
    """
    bv = BatchVerifier(plane="light")
    slices: List[Tuple[int, List[Tuple[int, int, Validator]]]] = []
    pre_errors: List[Optional[Exception]] = []
    needed_list: List[int] = []
    agg_done: dict = {}  # entry position -> result for aggregated commits
    off = 0
    for pos_e, entry in enumerate(entries):
        val_set, chain_id, commit, trust_level = entry[:4]
        if _is_aggregated(commit):
            commit_vals = entry[4] if len(entry) > 4 else None
            try:
                val_set.verify_commit_light_trusting(chain_id, commit,
                                                     trust_level,
                                                     commit_vals=commit_vals)
                agg_done[pos_e] = None
            except Exception as e:
                agg_done[pos_e] = e
            pre_errors.append(None)
            slices.append((off, []))
            needed_list.append(0)
            continue
        numer, denom = trust_level
        if denom == 0:
            pre_errors.append(ValueError("trustLevel has zero Denominator"))
            slices.append((off, []))
            needed_list.append(0)
            continue
        total_mul, overflow = safe_mul(val_set.total_voting_power(), numer)
        if overflow:
            pre_errors.append(OverflowError(
                "int64 overflow while calculating voting power needed. "
                "please provide smaller trustLevel numerator"
            ))
            slices.append((off, []))
            needed_list.append(0)
            continue
        pre_errors.append(None)
        needed_list.append(total_mul // denom)
        sb = commit.vote_sign_bytes_all(chain_id)
        addr_idx = val_set._addr_index()
        vals = val_set.validators
        cand: List[Tuple[int, int, Validator]] = []
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val_idx = addr_idx.get(cs.validator_address)
            if val_idx is not None:
                val = vals[val_idx]
                cand.append((idx, val_idx, val))
                bv.add(val.pub_key, sb[idx], cs.signature)
        slices.append((off, cand))
        off += len(cand)
    _, per_item = bv.verify()

    results: List[Optional[Exception]] = []
    for pos_e, (entry, pre_err, (start, cand), needed) in enumerate(zip(
            entries, pre_errors, slices, needed_list)):
        if pos_e in agg_done:
            results.append(agg_done[pos_e])
            continue
        if pre_err is not None:
            results.append(pre_err)
            continue
        commit = entry[2]
        tallied = 0
        seen: dict = {}
        err: Optional[Exception] = None
        for pos, (idx, val_idx, val) in enumerate(cand):
            if val_idx in seen:
                err = ValueError(
                    f"double vote from {val}: ({seen[val_idx]} and {idx})")
                break
            seen[val_idx] = idx
            if not per_item[start + pos]:
                err = ErrWrongSignature(idx, commit.signatures[idx].signature)
                break
            tallied += val.voting_power
            if tallied > needed:
                break
        else:
            err = ErrNotEnoughVotingPowerSigned(tallied, needed)
        results.append(err)
    return results


def _process_changes(changes: List[Validator]) -> Tuple[List[Validator], List[Validator]]:
    """Sort by address, reject dups/negatives, split updates/removals
    (validator_set.go:373)."""
    ordered = sorted(changes, key=lambda v: v.address)
    updates: List[Validator] = []
    removals: List[Validator] = []
    prev_addr = None
    for u in ordered:
        if u.address == prev_addr:
            raise ValueError(f"duplicate entry {u} in {ordered}")
        if u.voting_power < 0:
            raise ValueError(f"voting power can't be negative: {u.voting_power}")
        if u.voting_power > MAX_TOTAL_VOTING_POWER:
            raise ValueError(
                f"to prevent clipping/overflow, voting power can't be higher than "
                f"{MAX_TOTAL_VOTING_POWER}, got {u.voting_power}"
            )
        (removals if u.voting_power == 0 else updates).append(u)
        prev_addr = u.address
    return updates, removals
