"""SignedHeader + LightBlock (reference types/block.go SignedHeader,
types/light_block.go LightBlock; proto types.proto:137-146).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..libs import protowire as pw
from .block import Commit, Header
from .validator_set import ValidatorSet


@dataclass
class SignedHeader:
    header: Optional[Header] = None
    commit: Optional[Commit] = None

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(f"header belongs to another chain {self.header.chain_id!r}, "
                             f"not {chain_id!r}")
        if self.commit.height != self.header.height:
            raise ValueError(
                f"header and commit height mismatch: {self.header.height} vs {self.commit.height}")
        hhash, chash = self.header.hash(), self.commit.block_id.hash
        if hhash != chash:
            raise ValueError(
                f"commit signs block {chash.hex()}, header is block {hhash.hex()}")

    @property
    def height(self) -> int:
        return self.header.height if self.header else 0

    def encode(self) -> bytes:
        w = pw.Writer()
        if self.header is not None:
            w.message(1, self.header.encode())
        if self.commit is not None:
            w.message(2, self.commit.encode())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "SignedHeader":
        sh = SignedHeader()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                sh.header = Header.decode(v)
            elif fn == 2:
                sh.commit = Commit.decode(v)
        return sh


@dataclass
class LightBlock:
    signed_header: Optional[SignedHeader] = None
    validator_set: Optional[ValidatorSet] = None

    def validate_basic(self, chain_id: str) -> None:
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != self.validator_set.hash():
            raise ValueError(
                f"expected validators hash of header to match validator set hash "
                f"({self.signed_header.header.validators_hash.hex()}, "
                f"{self.validator_set.hash().hex()})")

    @property
    def height(self) -> int:
        return self.signed_header.height if self.signed_header else 0

    def encode(self) -> bytes:
        w = pw.Writer()
        if self.signed_header is not None:
            w.message(1, self.signed_header.encode())
        if self.validator_set is not None:
            w.message(2, self.validator_set.encode())
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "LightBlock":
        lb = LightBlock()
        for fn, _wt, v in pw.iter_fields(data):
            if fn == 1:
                lb.signed_header = SignedHeader.decode(v)
            elif fn == 2:
                lb.validator_set = ValidatorSet.decode(v)
        return lb
