"""Mempool (reference mempool/, SURVEY.md §2.5)."""

from .clist_mempool import CListMempool, MempoolError, TxCache  # noqa: F401
