"""Mempool (reference mempool/, SURVEY.md §2.5).

Two implementations behind one surface: the v0 CList port
(``clist_mempool.CListMempool``) and the production ingestion fast path
(``ingest.ShardedMempool`` — per-sender lanes, fee/priority eviction,
batched signature pre-verification; the v1 priority mempool's ordering
logic lives inside its lane eviction policy now).
"""

from .clist_mempool import CListMempool, MempoolError, TxCache  # noqa: F401
from .ingest import IngestPipeline, ShardedMempool  # noqa: F401
