"""Production ingestion fast path: batched tx pre-verification, sharded
per-sender mempool lanes, and async admission control.

PR 11 built the measurement surface (libs/txlife.py lifecycle tracing,
RPC/mempool telemetry, the open-loop ``ingest`` bench gated in
bench_compare); this module is the fast path those gates were built to
judge — the ROADMAP's "mempool + RPC built for millions of users" item.
Three stages, front to back:

**Async admission control** (:class:`IngestPipeline` +
:class:`AdmissionController`). ``broadcast_tx_*`` hands raw txs to a
bounded intake queue instead of running CheckTx inline on the event
loop. Overload is shed at the front door with a reason the client sees
(``queue-full``, ``sender-rate``, ``fee-floor``) as an explicit
non-zero CheckTx code — never a stall — and every shed lands on
``mempool_shed_txs_total{reason}``.

**Batched signature pre-verification.** Queued txs accumulate into
micro-batches (deadline- and size-triggered, the crypto/vote_batcher
discipline) and txs carrying the signed envelope (below) get their
ed25519 checks routed through ONE BatchVerifier call — riding
``batch_verify_stream``, the PR 9 multi-device pool, the device
circuit breaker, and host fallback, with verdicts byte-identical to the
scalar path by the crypto plane's existing differential guarantees. A
:func:`crypto.signcols.sign_columns_from_rows` hint makes tx packing
zero-copy for homogeneous batches, exactly like the vote-side
``SignColumns``. Verdicts land in a shared cache so the mempool's
scalar path — and post-commit recheck — never re-verify a signature
the batch already settled.

**Sharded per-sender mempool lanes** (:class:`ShardedMempool`).
Replaces the single CList mutex with N lanes keyed by the tx's sender
(the envelope pubkey; unsigned txs hash-shard), each lane its own
ordered dict + lock. Admission work (signature checks, the app CheckTx
call) runs outside the global mutex; only index/capacity bookkeeping
serializes. Eviction absorbs the v1 priority mempool's ordering logic
(that module is gone): when full, the lowest-(priority, newest) resident
across all lanes is evicted iff the incoming tx's priority is strictly
higher; reaping is a deterministic merge across lanes in
(priority desc, arrival asc) order; TTLs purge on update. Recheck after
commit is lane-local and reuses the cached pre-verification verdicts —
a commit triggers app rechecks only, never a signature re-verification
storm.

Signed-tx envelope (the ingest plane's native wire format)::

    b"stx1" || pubkey(32) || fee(8,BE) || nonce(8,BE) || payload || sig(64)

``sig`` is ed25519 over everything before it (the sign-bytes). Txs
without the magic are "unsigned": they pass pre-verification trivially
and carry fee 0 — the plane stays byte-compatible with every existing
app tx format. A tx WITH the magic but malformed (short, bad lengths)
is rejected before any device work, identically on both paths.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import itertools
import logging
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..abci.client import Client
from .clist_mempool import (
    MAX_TX_CACHE,
    ErrTxInCache,
    MempoolError,
    TxCache,
    _proto_overhead,
)

logger = logging.getLogger("tmtpu.mempool.ingest")

# -- signed-tx envelope -------------------------------------------------------

STX_MAGIC = b"stx1"
_STX_HEADER = len(STX_MAGIC) + 32 + 8 + 8  # magic | pubkey | fee | nonce
_STX_MIN = _STX_HEADER + 64  # + trailing sig

#: classification outcomes of :func:`parse_signed_tx`
UNSIGNED, SIGNED, MALFORMED = "unsigned", "signed", "malformed"


@dataclass(frozen=True)
class SignedTx:
    pubkey: bytes
    fee: int
    nonce: int
    payload: bytes
    sig: bytes
    sign_bytes: bytes


def make_signed_tx(priv_key, payload: bytes, nonce: int = 0,
                   fee: int = 0) -> bytes:
    """Encode + sign the envelope with a crypto.Ed25519PrivKey."""
    head = (STX_MAGIC + priv_key.pub_key().bytes()
            + struct.pack(">QQ", fee, nonce) + payload)
    return head + priv_key.sign(head)


def parse_signed_tx(tx: bytes) -> Tuple[str, Optional[SignedTx]]:
    """(status, envelope): ``unsigned`` for foreign formats, ``malformed``
    for magic-bearing txs that don't decode (identical verdict on the
    scalar and batched paths — malformed never reaches a verifier)."""
    if not tx.startswith(STX_MAGIC):
        return UNSIGNED, None
    if len(tx) < _STX_MIN:
        return MALFORMED, None
    fee, nonce = struct.unpack(">QQ", tx[36:52])
    return SIGNED, SignedTx(pubkey=tx[4:36], fee=fee, nonce=nonce,
                            payload=tx[_STX_HEADER:-64], sig=tx[-64:],
                            sign_bytes=tx[:-64])


def tx_fee(tx: bytes) -> int:
    status, stx = parse_signed_tx(tx)
    return stx.fee if status == SIGNED else 0


def tx_sender(tx: bytes) -> str:
    """Lane/rate-limit key: the envelope pubkey for signed txs; unsigned
    txs hash-shard (each is its own "sender", so per-sender controls
    never throttle foreign-format traffic as one client)."""
    status, stx = parse_signed_tx(tx)
    if status == SIGNED:
        return stx.pubkey.hex()
    return "h:" + hashlib.sha256(tx).hexdigest()[:16]


def conflict_hint(tx: bytes) -> Tuple[str, str]:
    """Conflict-group HINT for optimistic parallel execution
    (state/parallel.py): txs with different hints are *presumed*
    independent and speculated concurrently. This is only a scheduling
    hint — correctness never depends on it, because the executor
    validates actual read/write overlaps after speculation and
    re-executes anything the hint got wrong.

    ``("sender", pubkey_hex)`` for signed ``stx1`` envelopes (the ingest
    plane's per-sender lanes double as execution lanes);
    ``("key", k)`` for unsigned txs that strictly decode to the kvstore
    ``key=value`` format; ``("barrier", "")`` for validator-update
    ``val:`` txs and anything unparseable — those serialize in one
    block-ordered group."""
    status, stx = parse_signed_tx(tx)
    if status == SIGNED:
        return "sender", stx.pubkey.hex()
    if status == MALFORMED:
        return "barrier", ""
    try:
        raw = tx.decode("utf-8")
    except UnicodeDecodeError:
        return "barrier", ""
    if raw.startswith("val:"):
        return "barrier", ""
    return "key", raw.split("=", 1)[0] if "=" in raw else raw


def verify_signed_tx_scalar(tx: bytes) -> Tuple[bool, str]:
    """The SCALAR pre-verification spec the batched path must match
    byte-identically (differentially tested): (accept, reason)."""
    status, stx = parse_signed_tx(tx)
    if status == UNSIGNED:
        return True, UNSIGNED
    if status == MALFORMED:
        return False, MALFORMED
    from ..crypto import Ed25519PubKey

    ok = Ed25519PubKey(stx.pubkey).verify_signature(stx.sign_bytes, stx.sig)
    return bool(ok), "sig"


# -- sharded per-sender lanes -------------------------------------------------

DEFAULT_LANES = 8
VERDICT_CACHE_CAP = 16384


@dataclass
class LaneTx:
    """One resident tx (the mempool/v0 memTx + the v1 ordering fields)."""

    tx: bytes
    height: int
    gas_wanted: int
    senders: Set[str]
    key: bytes
    priority: int  # envelope fee, else app-assigned ResponseCheckTx.priority
    seq: int       # global admission order (reap/eviction tiebreak)
    time_s: float  # monotonic admission time (ttl_duration)
    lane: int


class _Lane:
    __slots__ = ("idx", "lock", "txs")

    def __init__(self, idx: int):
        self.idx = idx
        self.lock = threading.RLock()
        self.txs: "collections.OrderedDict[bytes, LaneTx]" = \
            collections.OrderedDict()


class ShardedMempool:
    """Drop-in for CListMempool (same surface the reactors, RPC layer,
    BlockExecutor, and WAL helpers consume) with per-sender lanes,
    fee/priority eviction, deterministic merged reap, and a shared
    pre-verification verdict cache.

    Locking: ``_admit_mtx`` guards the cross-lane index, dedup cache,
    and capacity counters; each lane's lock guards its dict. Acquisition
    order is always admit → lane. ``lock()``/``unlock()`` (held by
    BlockExecutor across commit+update) take everything.
    """

    def __init__(self, proxy_app: Client, height: int = 0,
                 max_txs: int = 5000, max_txs_bytes: int = 1073741824,
                 max_tx_bytes: int = 1048576, cache_size: int = MAX_TX_CACHE,
                 keep_invalid_txs_in_cache: bool = False,
                 recheck: bool = True, lanes: int = DEFAULT_LANES,
                 ttl_num_blocks: int = 0, ttl_duration: float = 0.0):
        self._proxy_app = proxy_app
        self.metrics = None  # MempoolMetrics, wired by the node
        self.txlife = None   # libs/txlife.py TxLifecycle, wired by the node
        self._wal = None     # MempoolWAL (clist_mempool.init_mempool_wal)
        self._height = height
        self._max_txs = max_txs
        self._max_txs_bytes = max_txs_bytes
        self._max_tx_bytes = max_tx_bytes
        self._keep_invalid = keep_invalid_txs_in_cache
        self._recheck_enabled = recheck
        self._ttl_num_blocks = ttl_num_blocks
        self._ttl_duration = ttl_duration
        self.cache = TxCache(cache_size)
        self.n_lanes = max(1, int(lanes))
        self._lanes = [_Lane(i) for i in range(self.n_lanes)]
        #: cross-lane index in ADMISSION order (seq order by construction:
        #: insertions happen under the admit mutex) — the gossip surface
        #: reads it straight off, no per-iteration sort
        self._index: "collections.OrderedDict[bytes, LaneTx]" = \
            collections.OrderedDict()
        self._txs_bytes = 0
        self._seq = itertools.count()
        self._admit_mtx = threading.RLock()
        #: pre-verification verdicts keyed by tx sha256: written by the
        #: batched pipeline AND the scalar path, consumed by both and by
        #: recheck — one signature check per tx lifetime
        self.sig_verdicts: "collections.OrderedDict[bytes, bool]" = \
            collections.OrderedDict()
        self._notified_txs_available = False
        self.tx_available_callbacks: List[Callable[[], None]] = []
        self.pre_check: Optional[Callable[[bytes], None]] = None
        self.post_check: Optional[Callable[[bytes, abci.ResponseCheckTx], None]] = None

    # -- Mempool interface (mempool/mempool.go:30) -------------------------

    def size(self) -> int:
        with self._admit_mtx:
            return len(self._index)

    def tx_bytes(self) -> int:
        with self._admit_mtx:
            return self._txs_bytes

    def lock(self) -> None:
        self._admit_mtx.acquire()
        for lane in self._lanes:
            lane.lock.acquire()

    def unlock(self) -> None:
        for lane in reversed(self._lanes):
            lane.lock.release()
        self._admit_mtx.release()

    def flush_app_conn(self) -> None:
        self._proxy_app.flush()

    def lane_for(self, tx: bytes) -> int:
        """Deterministic sender→lane shard (every node agrees)."""
        sender = tx_sender(tx)
        return int.from_bytes(
            hashlib.sha256(sender.encode()).digest()[:4], "big") % self.n_lanes

    # -- pre-verification (the scalar half of the differential contract) ----

    def _sig_verdict(self, key: bytes, tx: bytes) -> Tuple[bool, str]:
        """Cached batched verdict when the pipeline already settled this
        tx; the scalar spec otherwise. Writes its result back so recheck
        (and duplicate scalar submissions) stay signature-free."""
        status, _ = parse_signed_tx(tx)
        if status == UNSIGNED:
            return True, UNSIGNED
        if status == MALFORMED:
            return False, MALFORMED
        with self._admit_mtx:
            hit = self.sig_verdicts.get(key)
        m = self.metrics
        if hit is not None:
            if m is not None:
                m.preverify_cache_hits_total.labels("checktx").inc()
            return hit, "sig"
        ok, reason = verify_signed_tx_scalar(tx)
        self.store_sig_verdict(key, ok)
        if m is not None:
            m.preverified_txs_total.labels("scalar").inc()
        return ok, reason

    def store_sig_verdict(self, key: bytes, ok: bool) -> None:
        with self._admit_mtx:
            self.sig_verdicts[key] = ok
            self.sig_verdicts.move_to_end(key)
            while len(self.sig_verdicts) > VERDICT_CACHE_CAP:
                self.sig_verdicts.popitem(last=False)

    # -- admission ----------------------------------------------------------

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        """Admission: dedup → signature pre-verification (cache or
        scalar) → app CheckTx → capacity/eviction → lane insertion.
        Raises like CListMempool (ErrTxInCache, MempoolError) so the
        gossip reactor and legacy RPC paths work unchanged; ``sender``
        remains the gossiping PEER id (lane keying uses the tx itself).
        """
        key = hashlib.sha256(tx).digest()
        tl = self.txlife
        with self._admit_mtx:
            if len(tx) > self._max_tx_bytes:
                self._count_failed("too-large")
                self._mark_reject_or_phantom(tl, key)
                raise MempoolError(
                    f"tx too large. Max size is {self._max_tx_bytes}, "
                    f"but got {len(tx)}")
            if self.pre_check is not None:
                try:
                    self.pre_check(tx)
                except Exception:
                    if tl is not None:
                        tl.discard_phantom(key)
                    raise
            if not self.cache.push(tx):
                resident = self._index.get(key)
                if resident is not None and sender:
                    resident.senders.add(sender)
                # a duplicate is not a lifecycle event for the original —
                # but the retry's fresh rpc_received phantom must die
                self._count_failed("cache-dup")
                if tl is not None:
                    tl.discard_phantom(key)
                raise ErrTxInCache()

        # signature work OUTSIDE the admission mutex: this is the cost the
        # lanes exist to keep off the global serial path
        sig_ok, sig_reason = self._sig_verdict(key, tx)
        if tl is not None:
            tl.mark(key, "preverified",
                    outcome="accepted" if sig_ok else "rejected")
        if not sig_ok:
            reason = ("malformed-stx" if sig_reason == MALFORMED
                      else "invalid-sig")
            self._count_failed(reason)
            if not self._keep_invalid:
                with self._admit_mtx:
                    self.cache.remove(tx)
            return abci.ResponseCheckTx(
                code=1, log=f"signature pre-verification failed: {reason}",
                codespace="ingest")

        t0 = time.perf_counter()
        try:
            res = self._proxy_app.check_tx(abci.RequestCheckTx(tx=tx))
            checktx_s = time.perf_counter() - t0
            if self.post_check is not None:
                self.post_check(tx, res)
        except Exception:
            # broken app conn / raising post_check must not leak one
            # never-closed rpc_received record per attempt
            if tl is not None:
                tl.discard_phantom(key)
            raise
        m = self.metrics
        if m is not None:
            m.tx_size_bytes.observe(len(tx))
            m.checktx_latency_seconds.observe(checktx_s)
            if res.code != 0:
                m.failed_txs.labels("app-reject").inc()
        if not res.is_ok():
            if tl is not None:
                tl.mark(key, "checktx_done", outcome="rejected")
            if not self._keep_invalid:
                with self._admit_mtx:
                    self.cache.remove(tx)
            return res
        # the accepted checktx_done stamp waits for the capacity verdict:
        # stamping before it would leave a full-pool rejection with an
        # "accepted" stage it can never seal over (first stamp wins)

        status, stx = parse_signed_tx(tx)
        priority = stx.fee if status == SIGNED else getattr(res, "priority", 0)
        lane_idx = self.lane_for(tx)
        lane = self._lanes[lane_idx]
        with self._admit_mtx:
            if not self._make_room(priority, len(tx)):
                self._count_failed("full")
                self.cache.remove(tx)
                self._mark_reject_or_phantom(tl, key)
                raise MempoolError(
                    f"mempool is full: number of txs {len(self._index)} "
                    f"(max: {self._max_txs}), total bytes {self._txs_bytes}")
            if tl is not None:
                tl.mark(key, "checktx_done", outcome="accepted")
            mem_tx = LaneTx(tx=tx, height=self._height,
                            gas_wanted=res.gas_wanted,
                            senders={sender} if sender else set(), key=key,
                            priority=priority, seq=next(self._seq),
                            time_s=time.monotonic(), lane=lane_idx)
            with lane.lock:
                lane.txs[key] = mem_tx
            self._index[key] = mem_tx
            self._txs_bytes += len(tx)
            if self._wal is not None:
                self._wal.write(tx)
            if m is not None:
                m.admitted_txs_total.inc()
                self._set_depth_gauges()
            if tl is not None:
                tl.mark(key, "mempool_admitted")
            self._notify_txs_available()
        return res

    def _make_room(self, priority: int, nbytes: int) -> bool:
        """Caller holds the admit mutex. Evict strictly-lower-priority
        residents (lowest priority, newest first — the absorbed v1
        canAddTx/evictTx policy) until the incoming tx fits; False when
        it can't."""
        while (len(self._index) >= self._max_txs
               or self._txs_bytes + nbytes > self._max_txs_bytes):
            victim = min(self._index.values(), default=None,
                         key=lambda m: (m.priority, -m.seq))
            if victim is None or victim.priority >= priority:
                return False
            self._remove_resident(victim.key, reason="priority-evicted")
        return True

    def _remove_resident(self, key: bytes, reason: Optional[str] = None,
                         drop_cache: bool = True) -> Optional[LaneTx]:
        """Caller holds the admit mutex."""
        mem_tx = self._index.pop(key, None)
        if mem_tx is None:
            return None
        lane = self._lanes[mem_tx.lane]
        with lane.lock:
            lane.txs.pop(key, None)
        self._txs_bytes -= len(mem_tx.tx)
        if reason is not None:
            if self.metrics is not None:
                self.metrics.evicted_txs_total.labels(reason).inc()
            if drop_cache:
                self.cache.remove(mem_tx.tx)
        return mem_tx

    def _count_failed(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.failed_txs.labels(reason).inc()

    def _mark_reject_or_phantom(self, tl, key: bytes) -> None:
        """Capacity rejections: a retry of an already-known tx must not
        seal a bogus record over the original's live lifecycle (the
        CListMempool rule, same rationale)."""
        if tl is None:
            return
        if self.cache.has(key):
            tl.discard_phantom(key)
        else:
            tl.mark(key, "checktx_done", outcome="rejected")

    def _set_depth_gauges(self) -> None:
        """Caller holds the admit mutex; every mutation path ends here."""
        self.metrics.size.set(len(self._index))
        self.metrics.size_bytes.set(self._txs_bytes)

    # -- reaping (deterministic merge across lanes) -------------------------

    def _ordered_snapshot(self) -> List[LaneTx]:
        """All residents in (priority desc, arrival asc) order — the
        merged deterministic reap order every proposer derives
        identically from the same lane contents."""
        with self._admit_mtx:
            out = list(self._index.values())
        out.sort(key=lambda m: (-m.priority, m.seq))
        return out

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """(v1/mempool.go ReapMaxBytesMaxGas semantics: walk the priority
        order, skip what doesn't fit — a large high-fee tx can't starve
        the block.)"""
        out: List[bytes] = []
        total_bytes = 0
        total_gas = 0
        for mem_tx in self._ordered_snapshot():
            tx_size = len(mem_tx.tx) + _proto_overhead(len(mem_tx.tx))
            if max_bytes > -1 and total_bytes + tx_size > max_bytes:
                continue
            if max_gas > -1 and total_gas + mem_tx.gas_wanted > max_gas:
                continue
            total_bytes += tx_size
            total_gas += mem_tx.gas_wanted
            out.append(mem_tx.tx)
        return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        txs = [m.tx for m in self._ordered_snapshot()]
        return txs if n < 0 else txs[:n]

    # -- post-commit update + lane-local recheck ----------------------------

    def update(self, height: int, txs: List[bytes],
               deliver_tx_responses: List[abci.ResponseCheckTx],
               pre_check=None, post_check=None) -> None:
        """Caller must hold the lock (BlockExecutor.commit does)."""
        self._height = height
        self._notified_txs_available = False
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        tl = self.txlife
        for tx, res in zip(txs, deliver_tx_responses):
            key = hashlib.sha256(tx).digest()
            if res.is_ok():
                self.cache.push(tx)  # block resubmission of committed txs
                if tl is not None:
                    tl.mark(key, "committed", height=height)
            elif not self._keep_invalid:
                self.cache.remove(tx)
            self._remove_resident(key, reason=None)
        self._purge_expired()
        if self._index and self._recheck_enabled:
            self._recheck_lanes()
        if self._index:
            self._notify_txs_available()
        if self.metrics is not None:
            self._set_depth_gauges()

    def _purge_expired(self) -> None:
        """(v1/mempool.go purgeExpiredTxs) — block- and wall-clock TTLs."""
        if not (self._ttl_num_blocks or self._ttl_duration):
            return
        now = time.monotonic()
        for lane in self._lanes:
            with lane.lock:
                expired = [m.key for m in lane.txs.values() if
                           (self._ttl_num_blocks and
                            self._height - m.height > self._ttl_num_blocks)
                           or (self._ttl_duration and
                               now - m.time_s > self._ttl_duration)]
            for key in expired:
                self._remove_resident(key, reason="ttl-expired")

    def _recheck_lanes(self) -> None:
        """Lane-local post-block recheck: app CheckTx ONLY — the cached
        pre-verification verdict stands (signatures don't change when the
        app state does), so a commit never triggers a signature
        re-verification storm."""
        tl = self.txlife
        m = self.metrics
        for lane in self._lanes:
            with lane.lock:
                residents = list(lane.txs.values())
            for mem_tx in residents:
                if m is not None:
                    m.recheck_times.inc()
                    if mem_tx.key in self.sig_verdicts:
                        m.preverify_cache_hits_total.labels("recheck").inc()
                t0 = time.perf_counter()
                res = self._proxy_app.check_tx(abci.RequestCheckTx(
                    tx=mem_tx.tx, type=abci.CHECK_TX_TYPE_RECHECK))
                if m is not None:
                    m.recheck_latency_seconds.observe(
                        time.perf_counter() - t0)
                if tl is not None:
                    tl.mark(mem_tx.key, "rechecked",
                            outcome="accepted" if res.is_ok() else "rejected")
                if self.post_check is not None:
                    self.post_check(mem_tx.tx, res)
                if not res.is_ok():
                    self._remove_resident(
                        mem_tx.key, reason="recheck-failed",
                        drop_cache=not self._keep_invalid)

    def flush(self) -> None:
        with self._admit_mtx:
            n = len(self._index)
            if self.metrics is not None and n:
                self.metrics.evicted_txs_total.labels("flush").inc(n)
            for lane in self._lanes:
                with lane.lock:
                    lane.txs.clear()
            self._index.clear()
            self._txs_bytes = 0
            self.cache.reset()
            self.sig_verdicts.clear()
            if self.metrics is not None:
                self._set_depth_gauges()

    # -- gossip support (mempool/reactor.py) --------------------------------

    def entries_after(self, cursor: int) -> Tuple[List[LaneTx], int]:
        """Residents in global admission order (stable across lanes) after
        position ``cursor``; the reactor's per-peer iteration surface.
        The admission-ordered index makes this one O(n) copy, like the
        CList walk — no sort per gossip iteration."""
        with self._admit_mtx:
            items = list(self._index.values())
        return items[cursor:], len(items)

    def has_tx(self, tx: bytes) -> bool:
        with self._admit_mtx:
            return hashlib.sha256(tx).digest() in self._index

    def lane_depths(self) -> List[int]:
        return [len(lane.txs) for lane in self._lanes]

    # -- txs-available notification ----------------------------------------

    def _notify_txs_available(self) -> None:
        if not self._notified_txs_available and self._index:
            self._notified_txs_available = True
            for cb in self.tx_available_callbacks:
                cb()


# -- async admission control --------------------------------------------------

#: shed taxonomy (mempool_shed_txs_total{reason})
SHED_QUEUE_FULL = "queue-full"
SHED_SENDER_RATE = "sender-rate"
SHED_FEE_FLOOR = "fee-floor"

_BUCKET_CAP = 4096


class AdmissionController:
    """Reason-labeled shedding at the intake front door: bounded queue
    depth, a per-sender token-bucket rate, and a fee floor — all judged
    from the raw tx bytes BEFORE any verification or app work."""

    def __init__(self, queue_limit: int = 2048,
                 per_sender_rate: float = 0.0, fee_floor: int = 0):
        self.queue_limit = max(1, int(queue_limit))
        self.per_sender_rate = float(per_sender_rate)
        self.fee_floor = int(fee_floor)
        # sender -> [tokens, last_refill_monotonic]; LRU-bounded so a
        # sender-spoofing firehose can't grow memory
        self._buckets: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()

    def shed_reason(self, queue_depth: int, tx: bytes) -> Optional[str]:
        if queue_depth >= self.queue_limit:
            return SHED_QUEUE_FULL
        if self.fee_floor > 0 and tx_fee(tx) < self.fee_floor:
            return SHED_FEE_FLOOR
        if self.per_sender_rate > 0:
            sender = tx_sender(tx)
            now = time.monotonic()
            bucket = self._buckets.get(sender)
            if bucket is None:
                # burst allowance = 1s of the sustained rate (min 1)
                bucket = [max(1.0, self.per_sender_rate), now]
                self._buckets[sender] = bucket
                while len(self._buckets) > _BUCKET_CAP:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(sender)
                bucket[0] = min(max(1.0, self.per_sender_rate),
                                bucket[0] + (now - bucket[1])
                                * self.per_sender_rate)
                bucket[1] = now
            if bucket[0] < 1.0:
                return SHED_SENDER_RATE
            bucket[0] -= 1.0
        return None


DEFAULT_BATCH_MAX = 256
DEFAULT_BATCH_DEADLINE_S = 0.005


class _Item:
    __slots__ = ("tx", "key", "fut")

    def __init__(self, tx: bytes, key: bytes,
                 fut: Optional[asyncio.Future]):
        self.tx = tx
        self.key = key
        self.fut = fut


def _shed_response(reason: str) -> abci.ResponseCheckTx:
    return abci.ResponseCheckTx(code=1, log=f"shed: {reason}",
                                codespace="ingest")


class IngestPipeline:
    """The async front end ``broadcast_tx_*`` rides: admission control →
    micro-batched signature pre-verification → mempool admission.
    Event-loop-affine like the vote batcher: ``submit`` runs on the
    node's loop; signature batches verify off-loop (executor → device).
    """

    def __init__(self, mempool: ShardedMempool,
                 batch_max: int = DEFAULT_BATCH_MAX,
                 batch_deadline_s: float = DEFAULT_BATCH_DEADLINE_S,
                 queue_limit: int = 2048, per_sender_rate: float = 0.0,
                 fee_floor: int = 0, verifier_factory=None):
        self.mempool = mempool
        self.batch_max = max(1, int(batch_max))
        self.batch_deadline_s = batch_deadline_s
        self.admission = AdmissionController(queue_limit, per_sender_rate,
                                             fee_floor)
        self.metrics = None  # MempoolMetrics, wired by the node
        # BatchVerifier factory seam (tests pin backends / arm faults)
        if verifier_factory is None:
            from ..crypto.batch import BatchVerifier

            verifier_factory = lambda: BatchVerifier(plane="ingest")  # noqa: E731
        self._verifier_factory = verifier_factory
        self._pending: List[_Item] = []
        self._inflight = 0  # handed to a flush, not yet settled
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._flush_tasks: set = set()
        self.stats = collections.Counter()

    # -- intake --------------------------------------------------------------

    def _admit_or_shed(self, raw: bytes) -> Optional[str]:
        # the bound covers ALL unsettled work — queued AND mid-flush —
        # so a slow verify/admission stage produces backpressure instead
        # of an unbounded wave of in-flight batches
        reason = self.admission.shed_reason(
            len(self._pending) + self._inflight, raw)
        if reason is None:
            return None
        self.stats["shed"] += 1
        self.stats[f"shed_{reason}"] += 1
        if self.metrics is not None:
            self.metrics.shed_txs_total.labels(reason).inc()
        tl = self.mempool.txlife
        if tl is not None:
            # the front door refused before any verification: the
            # rpc_received phantom must not linger as a "lost" record
            tl.discard_phantom(hashlib.sha256(raw).digest())
        return reason

    def _enqueue(self, raw: bytes,
                 fut: Optional[asyncio.Future]) -> None:
        key = hashlib.sha256(raw).digest()
        self._pending.append(_Item(raw, key, fut))
        self.stats["enqueued"] += 1
        if len(self._pending) >= self.batch_max:
            self._do_flush()
        elif self._flush_handle is None:
            self._flush_handle = asyncio.get_running_loop().call_later(
                self.batch_deadline_s, self._do_flush)

    async def submit(self, raw: bytes,
                     sender: str = "") -> abci.ResponseCheckTx:
        """Admission verdict for one tx: a shed/rejection response (never
        an exception, never a stall) or the app's CheckTx response."""
        reason = self._admit_or_shed(raw)
        if reason is not None:
            return _shed_response(reason)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._enqueue(raw, fut)
        return await fut

    def submit_nowait(self, raw: bytes) -> bool:
        """Fire-and-forget intake (broadcast_tx_async): False when shed."""
        if self._admit_or_shed(raw) is not None:
            return False
        self._enqueue(raw, None)
        return True

    # -- micro-batch flush ---------------------------------------------------

    def _do_flush(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch = self._pending
        self._pending = []
        if not batch:
            return
        self._inflight += len(batch)
        t = asyncio.ensure_future(self._run_flush(batch))
        self._flush_tasks.add(t)
        t.add_done_callback(self._flush_tasks.discard)

    async def _run_flush(self, batch: List[_Item]) -> None:
        try:
            await self._run_flush_inner(batch)
        except Exception as e:  # pragma: no cover - defensive
            # last-resort settle: whatever escaped the inner handlers must
            # not strand a single future — every waiter gets an explicit
            # rejection instead of an infinite await
            logger.exception("ingest flush failed: %s", e)
            for item in batch:
                if item.fut is not None and not item.fut.done():
                    item.fut.set_result(abci.ResponseCheckTx(
                        code=1, log=f"ingest flush error: {e}",
                        codespace="ingest"))
        finally:
            self._inflight -= len(batch)

    async def _run_flush_inner(self, batch: List[_Item]) -> None:
        m = self.metrics
        if m is not None:
            # the bounded quantity: queued + ALL in-flight batches (this
            # one included — _do_flush counted it before scheduling us)
            m.intake_queue_depth.set(self.queue_depth())
        tl = self.mempool.txlife
        loop = asyncio.get_running_loop()
        # classify: one pass, malformed settled inline, signed rows
        # (not already settled by the verdict cache) collected for ONE
        # batched verification call
        rows: List[Tuple[_Item, SignedTx]] = []
        verdicts: Dict[bytes, Tuple[bool, str]] = {}
        for item in batch:
            status, stx = parse_signed_tx(item.tx)
            if status == UNSIGNED:
                verdicts[item.key] = (True, UNSIGNED)
            elif status == MALFORMED:
                verdicts[item.key] = (False, MALFORMED)
            else:
                cached = self.mempool.sig_verdicts.get(item.key)
                if cached is not None:
                    verdicts[item.key] = (cached, "sig")
                    self.stats["verdict_cache_hits"] += 1
                    if m is not None:
                        m.preverify_cache_hits_total.labels("batch").inc()
                else:
                    rows.append((item, stx))
        if rows:
            bv = self._verifier_factory()
            from ..crypto import Ed25519PubKey
            from ..crypto.signcols import sign_columns_from_rows

            msgs = []
            for item, stx in rows:
                bv.add(Ed25519PubKey(stx.pubkey), stx.sign_bytes, stx.sig)
                msgs.append(stx.sign_bytes)
            cols = sign_columns_from_rows(msgs)
            if cols is not None and hasattr(bv, "set_columns"):
                bv.set_columns(cols)
                self.stats["column_batches"] += 1
            # off the event loop: BatchVerifier routes host/device itself
            # (threshold, breaker, fallback — the PR 5-9 machinery)
            t0 = time.perf_counter()
            try:
                _all_ok, per_item = await loop.run_in_executor(
                    None, bv.verify)
            except Exception as e:  # pragma: no cover - defensive
                # BatchVerifier already host-falls-back on device errors;
                # anything escaping is a host-path bug — reject nothing,
                # settle scalar so no tx is ever lost to a crash here
                logger.exception("batched pre-verification failed: %s", e)
                per_item = [verify_signed_tx_scalar(item.tx)[0]
                            for item, _ in rows]
            if m is not None:
                m.preverify_latency_seconds.observe(
                    time.perf_counter() - t0)
            self.stats["batches"] += 1
            self.stats["batched_sigs"] += len(rows)
            for (item, _stx), ok in zip(rows, per_item):
                ok = bool(ok)
                verdicts[item.key] = (ok, "sig")
                self.mempool.store_sig_verdict(item.key, ok)
                if m is not None:
                    m.preverified_txs_total.labels(
                        "accepted" if ok else "rejected").inc()
        # settle, in arrival order (admission happens on the loop — the
        # in-proc app CheckTx is microseconds; the expensive signature
        # work is already behind us)
        for item in batch:
            ok, reason = verdicts[item.key]
            if tl is not None:
                tl.mark(item.key, "preverified",
                        outcome="accepted" if ok else "rejected")
            if not ok:
                label = ("malformed-stx" if reason == MALFORMED
                         else "invalid-sig")
                if m is not None:
                    m.failed_txs.labels(label).inc()
                res = abci.ResponseCheckTx(
                    code=1,
                    log=f"signature pre-verification failed: {label}",
                    codespace="ingest")
            else:
                try:
                    # NOTE: the app CheckTx runs on the loop, exactly like
                    # the legacy inline broadcast_tx_sync path did — fine
                    # for abci=local (microseconds); a remote socket/grpc
                    # app pays its RTT here either way (the availability
                    # callbacks are loop-affine, so this cannot move to a
                    # worker thread without reworking them)
                    res = self.mempool.check_tx(item.tx)
                except ErrTxInCache:
                    res = abci.ResponseCheckTx(code=1,
                                               log="tx already exists in cache",
                                               codespace="ingest")
                except MempoolError as e:
                    # backpressure/capacity: an explicit rejection the
                    # client can act on, not an RPC 500
                    res = abci.ResponseCheckTx(code=1, log=str(e),
                                               codespace="ingest")
                except Exception as e:
                    # a broken app connection (or raising pre_check) must
                    # reject THIS tx and keep settling the rest of the
                    # batch — an escaped exception here would strand every
                    # remaining future and stall their broadcast calls
                    logger.warning("admission failed for queued tx: %s", e)
                    res = abci.ResponseCheckTx(
                        code=1, log=f"admission error: {e}",
                        codespace="ingest")
            if item.fut is not None and not item.fut.done():
                item.fut.set_result(res)

    async def flush_now(self) -> None:
        """Force a flush and let it settle (tests / shutdown)."""
        self._do_flush()
        while self._flush_tasks:
            await asyncio.gather(*list(self._flush_tasks),
                                 return_exceptions=True)

    async def stop(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        await self.flush_now()

    def queue_depth(self) -> int:
        """Unsettled intake: queued + mid-flush (the bounded quantity)."""
        return len(self._pending) + self._inflight


# -- WAL replay ---------------------------------------------------------------

def replay_mempool_wal(mempool, wal_dir: str) -> Tuple[int, int]:
    """Re-admit every tx the MempoolWAL recorded (crash recovery: the
    lanes repopulate through the normal admission path, so dedup, sig
    verdicts and lane placement all re-derive). Returns
    (replayed, skipped) — cache-dup/invalid/full replays are skipped,
    never raised, so a replay is idempotent (no dup admits).

    An EXPLICIT operator/recovery tool, deliberately NOT run at node
    startup: the log is append-only and never pruned on commit, so a
    boot-time replay would re-admit already-committed txs — double
    execution for any app without its own replay protection. Prune or
    rotate the WAL before replaying after a long uptime."""
    import os

    path = os.path.join(wal_dir, "wal")
    if not os.path.exists(path):
        return 0, 0
    replayed = skipped = 0
    # replayed admits must not re-append to the very log being read
    wal, mempool._wal = mempool._wal, None
    try:
        with open(path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    tx = bytes.fromhex(line.decode())
                except ValueError:
                    continue  # torn tail
                try:
                    res = mempool.check_tx(tx)
                    if res.is_ok():
                        replayed += 1
                    else:
                        skipped += 1
                except (ErrTxInCache, MempoolError):
                    skipped += 1
    finally:
        mempool._wal = wal
    return replayed, skipped
