"""v1 priority mempool (reference mempool/v1/mempool.go:36 TxMempool).

Differences from v0 (clist FIFO):
* CheckTx responses carry an app-assigned ``priority`` (and ``sender``);
* when full, the lowest-priority resident tx is evicted IF the incoming
  priority is strictly higher (mempool.go canAddTx/evictTx);
* reaping returns txs in (priority desc, arrival asc) order;
* optional TTLs: txs expire after ``ttl_num_blocks`` blocks or
  ``ttl_duration`` seconds (mempool.go purgeExpiredTxs).

Shares the v0 cache + update/recheck semantics; the v0 gossip reactor works
unchanged against either implementation (both expose the same surface).
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..abci import types as abci
from ..abci.client import Client
from .clist_mempool import TxCache

logger = logging.getLogger("tmtpu.mempool.v1")


@dataclass(order=True)
class _WrappedTx:
    sort_key: tuple = field(init=False, repr=False)
    priority: int
    seq: int
    tx: bytes = field(compare=False)
    sender: str = field(compare=False, default="")
    gas_wanted: int = field(compare=False, default=0)
    height: int = field(compare=False, default=0)
    time_s: float = field(compare=False, default=0.0)

    def __post_init__(self):
        # heap pops lowest priority first (eviction order); ties: oldest last
        self.sort_key = (self.priority, -self.seq)


class PriorityMempool:
    def __init__(self, proxy_app: Client, height: int = 0,
                 max_txs: int = 5000, max_txs_bytes: int = 1 << 30,
                 max_tx_bytes: int = 1 << 20, cache_size: int = 10000,
                 keep_invalid_txs_in_cache: bool = False,
                 recheck: bool = True,
                 ttl_num_blocks: int = 0, ttl_duration: float = 0.0):
        self._proxy_app = proxy_app
        self.height = height
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.max_tx_bytes = max_tx_bytes
        self.recheck = recheck
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self.ttl_num_blocks = ttl_num_blocks
        self.ttl_duration = ttl_duration
        self.cache = TxCache(cache_size)
        self._txs: Dict[bytes, _WrappedTx] = {}   # hash -> wrapped
        self._bytes = 0
        self._seq = itertools.count()
        self.tx_available_callbacks: List[Callable[[], None]] = []
        # per-peer sent tracking lives in the reactor (shared with v0)
        self.tx_senders: Dict[bytes, set] = {}

    # -- the Mempool surface (mempool/mempool.go:30) -------------------------

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._bytes

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        if len(tx) > self.max_tx_bytes:
            return abci.ResponseCheckTx(code=1, log="tx too large")
        key = hashlib.sha256(tx).digest()
        if not self.cache.push(tx):
            if sender and key in self._txs:
                self.tx_senders.setdefault(key, set()).add(sender)
            return abci.ResponseCheckTx(code=0, log="tx already in cache")
        res = self._proxy_app.check_tx(abci.RequestCheckTx(tx=tx))
        if res.code != 0:
            if not self.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            return res
        wtx = _WrappedTx(priority=getattr(res, "priority", 0),
                         seq=next(self._seq), tx=tx, sender=sender,
                         gas_wanted=res.gas_wanted, height=self.height,
                         time_s=time.monotonic())
        if not self._can_add(wtx):
            self.cache.remove(tx)
            return abci.ResponseCheckTx(code=1, log="mempool is full")
        self._txs[key] = wtx
        self._bytes += len(tx)
        if sender:
            self.tx_senders.setdefault(key, set()).add(sender)
        for cb in self.tx_available_callbacks:
            cb()
        return res

    def _can_add(self, wtx: _WrappedTx) -> bool:
        """(v1/mempool.go canAddTx + eviction) evict strictly-lower-priority
        residents to make room; reject if still over capacity."""
        while (len(self._txs) >= self.max_txs
               or self._bytes + len(wtx.tx) > self.max_txs_bytes):
            victim = min(self._txs.values(), default=None)
            if victim is None or victim.priority >= wtx.priority:
                return False
            self._remove(hashlib.sha256(victim.tx).digest())
            logger.debug("evicted tx prio=%d for prio=%d", victim.priority,
                         wtx.priority)
        return True

    def _remove(self, key: bytes) -> None:
        wtx = self._txs.pop(key, None)
        if wtx is not None:
            self._bytes -= len(wtx.tx)
        self.tx_senders.pop(key, None)

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """(v1/mempool.go ReapMaxBytesMaxGas) priority desc, arrival asc."""
        ordered = sorted(self._txs.values(),
                         key=lambda w: (-w.priority, w.seq))
        out, total_b, total_g = [], 0, 0
        for w in ordered:
            if max_bytes >= 0 and total_b + len(w.tx) > max_bytes:
                continue
            if max_gas >= 0 and total_g + w.gas_wanted > max_gas:
                continue
            out.append(w.tx)
            total_b += len(w.tx)
            total_g += w.gas_wanted
        return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        ordered = sorted(self._txs.values(),
                         key=lambda w: (-w.priority, w.seq))
        return [w.tx for w in ordered[:max(0, n)]]

    def update(self, height: int, txs: List[bytes],
               deliver_results: Optional[List] = None) -> None:
        """(v1/mempool.go Update) drop committed txs, purge expired,
        recheck the rest."""
        self.height = height
        for i, tx in enumerate(txs):
            key = hashlib.sha256(tx).digest()
            code = (deliver_results[i].code
                    if deliver_results and i < len(deliver_results) else 0)
            if code == 0:
                self.cache.push(tx)
            elif not self.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            self._remove(key)
        self._purge_expired()
        if self.recheck and self._txs:
            self._recheck_txs()

    def _purge_expired(self) -> None:
        now = time.monotonic()
        for key, w in list(self._txs.items()):
            if self.ttl_num_blocks and self.height - w.height > self.ttl_num_blocks:
                self._remove(key)
                self.cache.remove(w.tx)
            elif self.ttl_duration and now - w.time_s > self.ttl_duration:
                self._remove(key)
                self.cache.remove(w.tx)

    def _recheck_txs(self) -> None:
        for key, w in list(self._txs.items()):
            res = self._proxy_app.check_tx(abci.RequestCheckTx(
                tx=w.tx, type=abci.CHECK_TX_TYPE_RECHECK))
            if res.code != 0:
                self._remove(key)
                if not self.keep_invalid_txs_in_cache:
                    self.cache.remove(w.tx)
            else:
                w.priority = getattr(res, "priority", w.priority)
                w.sort_key = (w.priority, -w.seq)

    def flush(self) -> None:
        self._txs.clear()
        self._bytes = 0
        self.tx_senders.clear()

    # reactor iteration surface (mempool/reactor gossip)
    def txs_snapshot(self) -> List[bytes]:
        return [w.tx for w in sorted(self._txs.values(),
                                     key=lambda w: (-w.priority, w.seq))]
