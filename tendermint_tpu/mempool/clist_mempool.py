"""FIFO mempool with async-style CheckTx and post-block recheck
(reference mempool/v0/clist_mempool.go:26).

The clist structure in the reference exists so per-peer gossip goroutines can
block at the tail; here an ordered dict + per-peer cursor indexes give the
same semantics for asyncio gossip tasks (see mempool reactor).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..abci.client import Client

MAX_TX_CACHE = 10000


class MempoolError(Exception):
    pass


class ErrTxInCache(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height when validated
    gas_wanted: int
    senders: Set[str]  # peers that sent us this tx (mempool/v0 memTx.senders)
    key: bytes = b""  # sha256(tx), precomputed for gossip bookkeeping


class TxCache:
    """LRU of recently seen tx hashes (mempool/cache.go)."""

    def __init__(self, size: int = MAX_TX_CACHE):
        self._size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        key = hashlib.sha256(tx).digest()
        if key in self._map:
            self._map.move_to_end(key)
            return False
        self._map[key] = None
        if len(self._map) > self._size:
            self._map.popitem(last=False)
        return True

    def has(self, key: bytes) -> bool:
        """Membership by precomputed sha256 key (no recency bump)."""
        return key in self._map

    def remove(self, tx: bytes) -> None:
        self._map.pop(hashlib.sha256(tx).digest(), None)

    def reset(self) -> None:
        self._map.clear()


class CListMempool:
    def __init__(self, proxy_app: Client, height: int = 0,
                 max_txs: int = 5000, max_txs_bytes: int = 1073741824,
                 max_tx_bytes: int = 1048576, cache_size: int = MAX_TX_CACHE,
                 keep_invalid_txs_in_cache: bool = False,
                 recheck: bool = True):
        self._proxy_app = proxy_app
        self.metrics = None  # MempoolMetrics, wired by the node
        self.txlife = None  # libs/txlife.py TxLifecycle, wired by the node
        self._wal = None  # optional tx log (mempool/v0 WAL, mempool.go InitWAL)
        self._height = height
        self._max_txs = max_txs
        self._max_txs_bytes = max_txs_bytes
        self._max_tx_bytes = max_tx_bytes
        self._keep_invalid = keep_invalid_txs_in_cache
        self._recheck_enabled = recheck
        self.cache = TxCache(cache_size)
        self._txs: "OrderedDict[bytes, MempoolTx]" = OrderedDict()  # key=sha256(tx)
        self._txs_bytes = 0
        self._mtx = threading.RLock()
        self._notified_txs_available = False
        self.tx_available_callbacks: List[Callable[[], None]] = []
        self.pre_check: Optional[Callable[[bytes], None]] = None
        self.post_check: Optional[Callable[[bytes, abci.ResponseCheckTx], None]] = None

    # -- Mempool interface (mempool/mempool.go:30) -------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._txs)

    def tx_bytes(self) -> int:
        with self._mtx:
            return self._txs_bytes

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def flush_app_conn(self) -> None:
        self._proxy_app.flush()

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        """Validate via app and add if OK (clist_mempool.go:203 CheckTx).

        Synchronous analogue of the reference's async path: the response
        callback logic (resCbFirstTime) runs inline.
        """
        with self._mtx:
            key = hashlib.sha256(tx).digest()
            tl = self.txlife
            if len(tx) > self._max_tx_bytes:
                self._count_failed("too-large")
                self._mark_capacity_reject(tl, key)
                raise MempoolError(
                    f"tx too large. Max size is {self._max_tx_bytes}, but got {len(tx)}")
            if len(self._txs) >= self._max_txs or \
                    self._txs_bytes + len(tx) > self._max_txs_bytes:
                self._count_failed("full")
                self._mark_capacity_reject(tl, key)
                raise MempoolError(
                    f"mempool is full: number of txs {len(self._txs)} "
                    f"(max: {self._max_txs}), total bytes {self._txs_bytes}")
            if self.pre_check is not None:
                try:
                    self.pre_check(tx)
                except Exception:
                    if tl is not None:
                        tl.discard_phantom(key)
                    raise
            if not self.cache.push(tx):
                # record the new sender for an existing tx (clist_mempool.go:239)
                existing = self._txs.get(key)
                if existing is not None and sender:
                    existing.senders.add(sender)
                # a duplicate is not a lifecycle event for the original
                # (still-live) record — count it, don't mark it; but a
                # retry of an already-SEALED tx just opened a fresh
                # record at rpc_received that nothing will ever close
                self._count_failed("cache-dup")
                if tl is not None:
                    tl.discard_phantom(key)
                raise ErrTxInCache()

            t0 = time.perf_counter()
            try:
                res = self._proxy_app.check_tx(abci.RequestCheckTx(tx=tx))
                checktx_s = time.perf_counter() - t0
                if self.post_check is not None:
                    self.post_check(tx, res)
            except Exception:
                # a broken app connection (or raising post_check) under a
                # broadcast storm must not leak one never-closed
                # rpc_received record per attempt; the checktx_done mark
                # below hasn't happened yet, so the record is still a
                # pure phantom
                if tl is not None:
                    tl.discard_phantom(key)
                raise
            if self.metrics is not None:
                self.metrics.tx_size_bytes.observe(len(tx))
                self.metrics.checktx_latency_seconds.observe(checktx_s)
                if res.code != 0:
                    self.metrics.failed_txs.labels("app-reject").inc()
            if tl is not None:
                tl.mark(key, "checktx_done",
                        outcome="accepted" if res.is_ok() else "rejected")
            if res.is_ok():
                mem_tx = MempoolTx(tx, self._height, res.gas_wanted,
                                   {sender} if sender else set(), key)
                self._txs[key] = mem_tx
                self._txs_bytes += len(tx)
                if self._wal is not None:
                    self._wal.write(tx)
                if self.metrics is not None:
                    self.metrics.admitted_txs_total.inc()
                    self._set_depth_gauges()
                if tl is not None:
                    tl.mark(key, "mempool_admitted")
                self._notify_txs_available()
            else:
                if not self._keep_invalid:
                    self.cache.remove(tx)
            return res

    def _count_failed(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.failed_txs.labels(reason).inc()

    def _mark_capacity_reject(self, tl, key: bytes) -> None:
        """The capacity checks run BEFORE the cache check (reference
        ordering), so a retry of an already-known tx can hit "full" too:
        a cached key must not seal a bogus rejected record over the
        ORIGINAL tx's lifecycle — drop the retry's rpc_received phantom
        instead. Only genuinely-new txs record the rejection."""
        if tl is None:
            return
        if self.cache.has(key):
            tl.discard_phantom(key)
        else:
            tl.mark(key, "checktx_done", outcome="rejected")

    def _set_depth_gauges(self) -> None:
        """Caller holds the lock. EVERY mutation path lands here — check_tx
        admission, update/recheck removals, and flush (which historically
        left the size gauge stale at the pre-flush depth)."""
        self.metrics.size.set(len(self._txs))
        self.metrics.size_bytes.set(self._txs_bytes)

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """(clist_mempool.go:521)"""
        with self._mtx:
            total_bytes = 0
            total_gas = 0
            out: List[bytes] = []
            for mem_tx in self._txs.values():
                tx_size = len(mem_tx.tx) + _proto_overhead(len(mem_tx.tx))
                if max_bytes > -1 and total_bytes + tx_size > max_bytes:
                    break
                new_gas = total_gas + mem_tx.gas_wanted
                if max_gas > -1 and new_gas > max_gas:
                    break
                total_bytes += tx_size
                total_gas = new_gas
                out.append(mem_tx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._mtx:
            txs = [m.tx for m in self._txs.values()]
            return txs if n < 0 else txs[:n]

    def update(self, height: int, txs: List[bytes],
               deliver_tx_responses: List[abci.ResponseCheckTx],
               pre_check=None, post_check=None) -> None:
        """Remove committed txs, recheck the rest (clist_mempool.go:594).
        Caller must hold the lock (BlockExecutor.commit does)."""
        self._height = height
        self._notified_txs_available = False
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        tl = self.txlife
        for tx, res in zip(txs, deliver_tx_responses):
            key = hashlib.sha256(tx).digest()
            if res.is_ok():
                self.cache.push(tx)  # committed: keep in cache to block resubmission
                if tl is not None:
                    # on the consensus path _finalize_commit already
                    # stamped committed (before apply_block reached us),
                    # making THIS mark the no-op; it is load-bearing on
                    # the non-consensus apply paths (fast sync)
                    tl.mark(key, "committed", height=height)
            elif not self._keep_invalid:
                self.cache.remove(tx)
            mem_tx = self._txs.pop(key, None)
            if mem_tx is not None:
                self._txs_bytes -= len(mem_tx.tx)
        if self._txs and self._recheck_enabled:
            self._recheck_txs()
        if self._txs:
            self._notify_txs_available()
        if self.metrics is not None:
            self._set_depth_gauges()

    def _recheck_txs(self) -> None:
        """Re-run CheckTx on remaining txs post-block (clist_mempool.go:641)."""
        tl = self.txlife
        for key in list(self._txs.keys()):
            mem_tx = self._txs[key]
            if self.metrics is not None:
                self.metrics.recheck_times.inc()
            t0 = time.perf_counter()
            res = self._proxy_app.check_tx(abci.RequestCheckTx(
                tx=mem_tx.tx, type=abci.CHECK_TX_TYPE_RECHECK))
            if self.metrics is not None:
                self.metrics.recheck_latency_seconds.observe(
                    time.perf_counter() - t0)
            if tl is not None:
                tl.mark(key, "rechecked",
                        outcome="accepted" if res.is_ok() else "rejected")
            if self.post_check is not None:
                self.post_check(mem_tx.tx, res)
            if not res.is_ok():
                del self._txs[key]
                self._txs_bytes -= len(mem_tx.tx)
                if self.metrics is not None:
                    self.metrics.evicted_txs_total.labels(
                        "recheck-failed").inc()
                if not self._keep_invalid:
                    self.cache.remove(mem_tx.tx)

    def flush(self) -> None:
        with self._mtx:
            if self.metrics is not None and self._txs:
                self.metrics.evicted_txs_total.labels("flush").inc(
                    len(self._txs))
            self._txs.clear()
            self._txs_bytes = 0
            self.cache.reset()
            if self.metrics is not None:
                self._set_depth_gauges()

    # -- gossip support ----------------------------------------------------

    def entries_after(self, cursor: int) -> Tuple[List[MempoolTx], int]:
        """Txs in insertion order after position `cursor`; returns new cursor.
        A stable iteration surface for reactor gossip tasks."""
        with self._mtx:
            items = list(self._txs.values())
        return items[cursor:], len(items)

    def has_tx(self, tx: bytes) -> bool:
        with self._mtx:
            return hashlib.sha256(tx).digest() in self._txs

    # -- txs-available notification (clist_mempool.go TxsAvailable) --------

    def _notify_txs_available(self) -> None:
        if not self._notified_txs_available and self._txs:
            self._notified_txs_available = True
            for cb in self.tx_available_callbacks:
                cb()


def _proto_overhead(n: int) -> int:
    from ..types.tx import compute_proto_size_overhead

    return compute_proto_size_overhead(n)


class MempoolWAL:
    """Append-only tx log (reference mempool WAL, clist_mempool.go InitWAL):
    newline-delimited hex, flushed per write — a recovery/debugging trail of
    every tx that entered the mempool."""

    def __init__(self, wal_dir: str):
        import os

        os.makedirs(wal_dir, exist_ok=True)
        path = os.path.join(wal_dir, "wal")
        self._repair_tail(path)
        self._f = open(path, "ab")

    @staticmethod
    def _repair_tail(path: str) -> None:
        """Repair-on-open: truncate a partial (newline-less) tail line a
        crash left behind. Appending after it would MERGE the torn hex
        with the next tx's hex — often still valid hex, so replay would
        admit a bogus tx and silently lose the first post-restart one."""
        import os

        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size == 0:
            return
        with open(path, "rb") as f:
            f.seek(max(0, size - 1))
            if f.read(1) == b"\n":
                return
            # only the tail line matters; a line is at most one tx's hex
            # (2*max_tx_bytes+1), so a bounded tail read covers it
            tail_len = min(size, 4 * 1024 * 1024)
            f.seek(size - tail_len)
            raw = f.read()
        cut = raw.rfind(b"\n")
        if cut < 0 and tail_len < size:
            # torn line longer than the window (pathological): scan whole
            with open(path, "rb") as f:
                raw = f.read()
            tail_len, cut = size, raw.rfind(b"\n")
        good = 0 if cut < 0 else size - tail_len + cut + 1
        os.truncate(path, good)

    def write(self, tx: bytes) -> None:
        from ..libs.faults import faults

        # torn-write seam at the byte-emit point: a fired site persists a
        # partial line (what a crash mid-append leaves); replay skips the
        # undecodable line and stays idempotent
        self._f.write(faults.tear("mempool.wal_torn",
                                  tx.hex().encode() + b"\n"))
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except ValueError:
            pass


def init_mempool_wal(mempool, wal_dir: str) -> None:
    """(mempool.go InitWAL)"""
    mempool._wal = MempoolWAL(wal_dir)
