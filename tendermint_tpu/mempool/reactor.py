"""Mempool reactor: tx gossip on channel 0x30 (reference mempool/v0/reactor.go:23).

One async broadcast task per peer walks the mempool in insertion order and
skips peers that already sent us the tx (memTx.senders).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List

from ..libs import protowire as pw
from ..p2p import MEMPOOL_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from .clist_mempool import CListMempool, ErrTxInCache, MempoolError

logger = logging.getLogger("tmtpu.mempool.reactor")


def encode_txs(txs: List[bytes]) -> bytes:
    """mempool Message{Txs} (proto/tendermint/mempool/types.proto)."""
    inner = pw.Writer()
    for tx in txs:
        inner.bytes(1, tx)
    w = pw.Writer()
    w.message(1, inner.finish())
    return w.finish()


def decode_txs(data: bytes) -> List[bytes]:
    out: List[bytes] = []
    for fn, _wt, v in pw.iter_fields(data):
        if fn == 1:
            for ifn, _iwt, iv in pw.iter_fields(v):
                if ifn == 1:
                    out.append(iv)
    return out


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, broadcast: bool = True,
                 gossip_sleep: float = 0.01):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast_enabled = broadcast
        self._gossip_sleep = gossip_sleep
        self._tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    async def add_peer(self, peer: Peer) -> None:
        if self.broadcast_enabled:
            self._tasks[peer.id] = asyncio.create_task(
                self._broadcast_tx_routine(peer))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        t = self._tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    async def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        for tx in decode_txs(msg_bytes):
            try:
                self.mempool.check_tx(tx, sender=peer.id)
            except ErrTxInCache:
                pass
            except MempoolError as e:
                logger.debug("rejected gossiped tx: %s", e)

    async def _broadcast_tx_routine(self, peer: Peer) -> None:
        """(mempool/v0/reactor.go:216 broadcastTxRoutine)

        Tracks sent tx hashes per peer (positional cursors shift when commits
        evict txs); resends are deduped by the remote's tx cache anyway.
        """
        sent: set = set()
        try:
            while peer.is_running():
                entries, _ = self.mempool.entries_after(0)
                live = set()
                sent_any = False
                for mem_tx in entries:
                    live.add(mem_tx.key)
                    if mem_tx.key in sent or peer.id in mem_tx.senders:
                        continue
                    if peer.try_send(MEMPOOL_CHANNEL, encode_txs([mem_tx.tx])):
                        sent.add(mem_tx.key)
                        sent_any = True
                        tl = getattr(self.mempool, "txlife", None)
                        if tl is not None:
                            # first stamp wins: per-peer routines racing
                            # here still record the FIRST outbound gossip
                            tl.mark(mem_tx.key, "first_gossip")
                sent &= live  # forget evicted txs
                await asyncio.sleep(0 if sent_any else self._gossip_sleep)
        except asyncio.CancelledError:
            pass
