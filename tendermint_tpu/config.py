"""Master node configuration: 9 sections + TOML round-trip.

Mirrors the reference's config system (config/config.go:66 Config struct:
Base :158, RPC :305, P2P :517, Mempool :686, StateSync :792, FastSync :882,
Consensus :917, Storage :1081, TxIndex :1117, Instrumentation :1148) and its
TOML template writer (config/toml.go). Reading uses stdlib ``tomllib`` when
available and the 3.10-safe subset reader (libs/toml_compat.py) otherwise;
writing emits a commented template so an operator can hand-edit the file the
same way the reference's ``tendermint init`` output allows.

Defaults match the reference's DefaultConfig() values where they translate
(Go durations become float seconds).
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field, fields
from typing import List, Optional

from .consensus.config import ConsensusConfig

DEFAULT_DIR = ".tmtpu"
CONFIG_DIR = "config"
DATA_DIR = "data"


@dataclass
class BaseConfig:
    """(config/config.go:158 BaseConfig)"""

    chain_id: str = ""
    moniker: str = "anonymous"
    fast_sync: bool = True
    db_backend: str = "sqlite"       # sqlite | mem (tm-db analog, libs/db.py)
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "plain"        # plain | json
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""
    # hex ed25519 pubkey of the authorized remote signer; when set, the
    # SecretConnection handshake on priv_validator_laddr pins it
    priv_validator_signer_key: str = ""
    node_key_file: str = "config/node_key.json"
    abci: str = "local"              # local | socket | grpc
    proxy_app: str = "kvstore"       # app name or tcp://host:port when socket
    filter_peers: bool = False


@dataclass
class RPCConfig:
    """(config/config.go:305 RPCConfig)"""

    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: List[str] = field(default_factory=list)
    grpc_laddr: str = ""
    unsafe: bool = False
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit: float = 10.0
    max_body_bytes: int = 1000000
    max_header_bytes: int = 1 << 20
    pprof_laddr: str = ""
    # per-socket bounded websocket send queue: a subscriber that stops
    # reading is EVICTED when its queue overflows (rpc/server._WsFanout)
    # instead of backing up the event bus
    ws_send_queue_size: int = 256


@dataclass
class P2PConfig:
    """(config/config.go:517 P2PConfig)"""

    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    upnp: bool = False
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    unconditional_peer_ids: str = ""
    persistent_peers_max_dial_period: float = 0.0
    flush_throttle_timeout: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0


@dataclass
class MempoolConfig:
    """(config/config.go:686 MempoolConfig — grown the ingestion fast
    path's knobs: lane topology and admission control, mempool/ingest.py)"""

    # v2 = sharded per-sender lanes + async admission + batched signature
    # pre-verification (mempool/ingest.py, the default); v0 = the CList
    # port (mempool/clist_mempool.py)
    version: str = "v2"
    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    size: int = 5000
    max_txs_bytes: int = 1073741824
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1048576
    max_batch_bytes: int = 0
    ttl_duration: float = 0.0
    ttl_num_blocks: int = 0
    # -- ingestion fast path (version v2 only) ------------------------------
    lanes: int = 8                     # per-sender mempool lanes
    ingest_queue_size: int = 2048      # intake bound; beyond it: queue-full
    ingest_batch_max: int = 256        # pre-verification micro-batch cap
    ingest_batch_deadline_s: float = 0.005  # flush deadline after first tx
    ingest_per_sender_rate: float = 0.0  # tx/s per sender; 0 disables
    ingest_fee_floor: int = 0          # min envelope fee; 0 admits unsigned


@dataclass
class StateSyncConfig:
    """(config/config.go:792 StateSyncConfig)"""

    enable: bool = False
    rpc_servers: List[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: float = 168 * 3600.0
    discovery_time: float = 15.0
    temp_dir: str = ""
    # chunk fetch plane (statesync/syncer.py reads these through node.py;
    # TMTPU_STATESYNC_CHUNK_TIMEOUT / TMTPU_STATESYNC_CHUNK_FETCHERS
    # override per-process so chaos cells can tighten them without
    # monkeypatching)
    chunk_request_timeout: float = 10.0
    chunk_fetchers: int = 4
    # adversarial resilience: snapshot re-discovery rounds before giving
    # up (then node.py falls back to fast sync from genesis), and
    # consecutive bad chunks/snapshots before a peer is banned
    discovery_attempts: int = 4
    peer_ban_threshold: int = 3


@dataclass
class FastSyncConfig:
    """(config/config.go:882 FastSyncConfig)"""

    version: str = "v0"


@dataclass
class ExecutionConfig:
    """Execution plane (state/execution.py + state/parallel.py). No
    reference analog — tendermint executes DeliverTx serially; this build
    grows an optimistic parallel path over it."""

    # v1 = optimistic parallel block execution: conflict-grouped
    # speculation + validation + serial re-execution of conflicts, with
    # byte-identical outputs and automatic per-block fallback to serial
    # (state/parallel.py); v0 = the serial spec path only
    version: str = "v1"
    workers: int = 4            # speculation thread pool width
    min_parallel_txs: int = 2   # below this, serial is always cheaper


@dataclass
class LightServeConfig:
    """Light-client serving plane (light/serve.py). No reference analog —
    tendermint serves light clients one scalar RPC at a time; this build
    coalesces a population of them into shared device batches."""

    enable: bool = True
    # coalescer: flush after this many ms from the first queued request,
    # or as soon as flush_max requests accumulate, whichever first
    flush_deadline_ms: float = 2.0
    flush_max: int = 64
    queue_limit: int = 4096            # pending verifies; beyond: queue-full
    cache_capacity: int = 1024         # header/commit docs resident
    verdict_cache_size: int = 4096     # remembered verify verdicts
    prefetch_limit: int = 16           # bisection-skeleton heights pinned
    per_client_rate: float = 0.0       # requests/s per client id; 0 disables
    per_client_burst: int = 16
    abuse_ban_threshold: int = 8       # consecutive rate strikes before ban
    trusting_period_s: float = 14 * 24 * 3600.0
    max_clock_drift_s: float = 10.0


@dataclass
class StorageConfig:
    """(config/config.go:1081 StorageConfig)"""

    discard_abci_responses: bool = False


@dataclass
class TxIndexConfig:
    """(config/config.go:1117 TxIndexConfig)"""

    indexer: str = "kv"              # kv | null | psql (SQL event sink)
    # connection for indexer="psql" (reference config.go PsqlConn); here a
    # sqlite path — empty means <data>/events.sqlite (see state/sink.py)
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    """(config/config.go:1148 InstrumentationConfig)"""

    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "tendermint"


_SECTIONS = [
    ("rpc", RPCConfig), ("p2p", P2PConfig), ("mempool", MempoolConfig),
    ("statesync", StateSyncConfig), ("fastsync", FastSyncConfig),
    ("execution", ExecutionConfig), ("lightserve", LightServeConfig),
    ("consensus", ConsensusConfig), ("storage", StorageConfig),
    ("tx_index", TxIndexConfig), ("instrumentation", InstrumentationConfig),
]


@dataclass
class Config:
    """The master config (config/config.go:66). ``root_dir`` is the home."""

    root_dir: str = DEFAULT_DIR
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    fastsync: FastSyncConfig = field(default_factory=FastSyncConfig)
    execution: ExecutionConfig = field(default_factory=ExecutionConfig)
    lightserve: LightServeConfig = field(default_factory=LightServeConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)

    # -- path helpers (reference config.go rootify) -------------------------

    def _rootify(self, path: str) -> str:
        if os.path.isabs(path):
            return path
        return os.path.join(self.root_dir, path)

    def genesis_file(self) -> str:
        return self._rootify(self.base.genesis_file)

    def priv_validator_key_file(self) -> str:
        return self._rootify(self.base.priv_validator_key_file)

    def priv_validator_state_file(self) -> str:
        return self._rootify(self.base.priv_validator_state_file)

    def node_key_file(self) -> str:
        return self._rootify(self.base.node_key_file)

    def db_dir(self) -> str:
        return self._rootify(self.base.db_dir)

    def wal_file(self) -> str:
        wf = self.consensus.wal_file or os.path.join("data", "cs.wal", "wal")
        return self._rootify(wf)

    # -- validation (per-section ValidateBasic) ------------------------------

    def validate_basic(self) -> None:
        if self.base.db_backend not in ("sqlite", "mem"):
            raise ValueError(f"unknown db_backend {self.base.db_backend!r}")
        if self.base.abci not in ("local", "socket", "grpc"):
            raise ValueError(f"unknown abci mode {self.base.abci!r}")
        # "v1" is accepted as an alias for the lanes path: its priority
        # ordering/eviction/TTL semantics live in the lane eviction policy
        if self.mempool.version not in ("v0", "v1", "v2"):
            raise ValueError(f"unknown mempool version {self.mempool.version!r}")
        if self.mempool.size <= 0:
            raise ValueError("mempool.size must be positive")
        if self.mempool.cache_size < 0:
            raise ValueError("mempool.cache_size must be non-negative")
        if self.mempool.lanes <= 0:
            raise ValueError("mempool.lanes must be positive")
        if self.mempool.ingest_queue_size <= 0:
            raise ValueError("mempool.ingest_queue_size must be positive")
        for name in ("timeout_propose", "timeout_prevote", "timeout_precommit",
                     "timeout_commit"):
            if getattr(self.consensus, name) < 0:
                raise ValueError(f"consensus.{name} cannot be negative")
        if self.statesync.enable:
            if len(self.statesync.rpc_servers) < 2:
                raise ValueError("statesync requires >= 2 rpc_servers")
            if self.statesync.trust_height <= 0:
                raise ValueError("statesync.trust_height must be set")
        if self.fastsync.version not in ("v0",):
            raise ValueError(f"unknown fastsync version {self.fastsync.version!r}")
        if self.execution.version not in ("v0", "v1"):
            raise ValueError(f"unknown execution version {self.execution.version!r}")
        if self.execution.workers <= 0:
            raise ValueError("execution.workers must be positive")
        if self.execution.min_parallel_txs < 0:
            raise ValueError("execution.min_parallel_txs cannot be negative")
        if self.lightserve.flush_max <= 0:
            raise ValueError("lightserve.flush_max must be positive")
        if self.lightserve.flush_deadline_ms < 0:
            raise ValueError("lightserve.flush_deadline_ms cannot be negative")
        if self.lightserve.cache_capacity <= 0:
            raise ValueError("lightserve.cache_capacity must be positive")
        if self.lightserve.queue_limit <= 0:
            raise ValueError("lightserve.queue_limit must be positive")
        if self.rpc.ws_send_queue_size <= 0:
            raise ValueError("rpc.ws_send_queue_size must be positive")
        if self.tx_index.indexer not in ("kv", "null", "psql"):
            raise ValueError(f"unknown indexer {self.tx_index.indexer!r}")

    # -- TOML round-trip -----------------------------------------------------

    def save(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(self.root_dir, CONFIG_DIR, "config.toml")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_toml())
        return path

    def to_toml(self) -> str:
        out = ["# tendermint-tpu node configuration",
               "# edit and restart the node to apply\n"]
        for fld in fields(BaseConfig):
            out.append(_toml_kv(fld.name, getattr(self.base, fld.name)))
        for section, _cls in _SECTIONS:
            cfg = getattr(self, section)
            out.append(f"\n[{section}]")
            for fld in fields(cfg):
                out.append(_toml_kv(fld.name, getattr(cfg, fld.name)))
        return "\n".join(out) + "\n"

    @classmethod
    def load(cls, root_dir: str, path: Optional[str] = None) -> "Config":
        from .libs import toml_compat

        path = path or os.path.join(root_dir, CONFIG_DIR, "config.toml")
        with open(path, "rb") as f:
            doc = toml_compat.load(f)
        cfg = cls(root_dir=root_dir)
        base_fields = {f.name for f in fields(BaseConfig)}
        for k, v in doc.items():
            if k in base_fields:
                setattr(cfg.base, k, v)
        for section, seccls in _SECTIONS:
            sec = doc.get(section)
            if not isinstance(sec, dict):
                continue
            target = getattr(cfg, section)
            known = {f.name for f in fields(seccls)}
            for k, v in sec.items():
                if k in known:
                    setattr(target, k, v)
        return cfg


def _toml_kv(key: str, value) -> str:
    if isinstance(value, bool):
        return f"{key} = {'true' if value else 'false'}"
    if isinstance(value, (int, float)):
        return f"{key} = {value}"
    if isinstance(value, str):
        return f'{key} = {_toml_str(value)}'
    if isinstance(value, list):
        inner = ", ".join(_toml_str(v) if isinstance(v, str) else str(v) for v in value)
        return f"{key} = [{inner}]"
    raise TypeError(f"cannot encode config value {key}={value!r}")


def _toml_str(v: str) -> str:
    return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'


def default_config(root_dir: str = DEFAULT_DIR) -> Config:
    return Config(root_dir=root_dir)


def test_config(root_dir: str) -> Config:
    """Fast-timeout config for tests/localnets (reference ResetTestRoot)."""
    from .consensus.config import test_consensus_config

    cfg = Config(root_dir=root_dir)
    cfg.consensus = test_consensus_config()
    cfg.base.db_backend = "mem"
    return cfg


test_config.__test__ = False  # not a pytest test despite the name
