"""Evidence subsystem (reference evidence/, SURVEY.md §2.9)."""

from .pool import EvidencePool  # noqa: F401
from .verify import verify_duplicate_vote, verify_evidence  # noqa: F401
