"""Evidence reactor: gossips pending evidence on channel 0x38
(reference evidence/reactor.go:16,30).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List

from ..libs import protowire as pw
from ..p2p import EVIDENCE_CHANNEL
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from ..types.evidence import decode_evidence
from .pool import EvidencePool

logger = logging.getLogger("tmtpu.evidence.reactor")


def encode_evidence_list_msg(evs) -> bytes:
    """evidence.proto List message: repeated Evidence (oneof-wrapped)."""
    w = pw.Writer()
    for ev in evs:
        w.message(1, ev.wrapped())
    return w.finish()


def decode_evidence_list_msg(data: bytes):
    return [decode_evidence(v) for fn, _wt, v in pw.iter_fields(data) if fn == 1]


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool, gossip_sleep: float = 0.1):
        super().__init__("EVIDENCE")
        self.pool = pool
        self._gossip_sleep = gossip_sleep
        self._tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=6)]

    async def add_peer(self, peer: Peer) -> None:
        self._tasks[peer.id] = asyncio.create_task(self._broadcast_routine(peer))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        t = self._tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    async def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        from .verify import ErrNoEvidenceData

        for ev in decode_evidence_list_msg(msg_bytes):
            try:
                self.pool.add_evidence(ev)
            except ErrNoEvidenceData as e:
                # we're behind or pruned: can't judge — don't punish the peer
                # (reference evidence/reactor.go only bans on ErrInvalidEvidence)
                logger.debug("cannot verify evidence from %s yet: %s", peer.id[:8], e)
            except ValueError as e:
                logger.info("invalid evidence from %s: %s", peer.id[:8], e)
                await self.switch.stop_peer_for_error(peer, str(e))
                return

    async def _broadcast_routine(self, peer: Peer) -> None:
        """(evidence/reactor.go:30 broadcastEvidenceRoutine)"""
        sent: set = set()
        try:
            while peer.is_running():
                pending, _ = self.pool.pending_evidence(-1)
                live = set()
                for ev in pending:
                    h = ev.hash()
                    live.add(h)
                    if h in sent:
                        continue
                    if peer.try_send(EVIDENCE_CHANNEL, encode_evidence_list_msg([ev])):
                        sent.add(h)
                sent &= live
                await asyncio.sleep(self._gossip_sleep)
        except asyncio.CancelledError:
            pass
