"""Evidence pool (reference evidence/pool.go:28): persists pending/committed
evidence, buffers consensus-reported conflicting votes until height advances,
prunes expired evidence on update.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..libs.db import DB
from ..types import DuplicateVoteEvidence, Evidence
from ..types.evidence import decode_evidence
from ..types.vote import Vote
from .verify import verify_evidence

logger = logging.getLogger("tmtpu.evidence")

_PENDING_PREFIX = b"ev-pending:"
_COMMITTED_PREFIX = b"ev-committed:"


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + ev.height().to_bytes(8, "big") + ev.hash()


class EvidencePool:
    def __init__(self, db: DB, state_store, block_store):
        self._db = db
        self.state_store = state_store
        self.block_store = block_store
        self._mtx = threading.Lock()
        # votes reported by consensus before their height is committed
        # (pool.go:459 consensusBuffer)
        self._consensus_buffer: List[Tuple[Vote, Vote]] = []
        self._pending_bytes = 0
        self.state = None  # set by set_state/update

    def set_state(self, state) -> None:
        self.state = state

    # -- queries -----------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        """(pool.go:80 PendingEvidence)"""
        out: List[Evidence] = []
        size = 0
        for _k, v in self._db.iterate_prefix(_PENDING_PREFIX):
            ev = decode_evidence(v)
            # EvidenceList wire overhead per item
            item_size = len(ev.wrapped()) + 4
            if max_bytes >= 0 and size + item_size > max_bytes:
                break
            out.append(ev)
            size += item_size
        return out, size

    def is_pending(self, ev: Evidence) -> bool:
        return self._db.has(_key(_PENDING_PREFIX, ev))

    def is_committed(self, ev: Evidence) -> bool:
        return self._db.has(_key(_COMMITTED_PREFIX, ev))

    # -- adding ------------------------------------------------------------

    def add_evidence(self, ev: Evidence) -> None:
        """(pool.go:134 AddEvidence)"""
        with self._mtx:
            if self.is_pending(ev) or self.is_committed(ev):
                return
            ev.validate_basic()
            verify_evidence(ev, self.state, self.state_store, self.block_store)
            self._db.set(_key(_PENDING_PREFIX, ev), ev.wrapped())
            logger.info("verified new evidence of byzantine behaviour: %s h=%d",
                        ev.abci_evidence_type(), ev.height())

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """(pool.go:179) — buffered until the next Update."""
        with self._mtx:
            self._consensus_buffer.append((vote_a, vote_b))

    def check_evidence(self, evidence: List[Evidence]) -> None:
        """Validate a block's evidence list (pool.go:192 CheckEvidence)."""
        seen = set()
        for ev in evidence:
            if not self.is_pending(ev) and not self.is_committed(ev):
                ev.validate_basic()
                verify_evidence(ev, self.state, self.state_store, self.block_store)
            if self.is_committed(ev):
                raise ValueError(f"evidence was already committed: {ev.hash().hex()}")
            if ev.hash() in seen:
                raise ValueError(f"duplicate evidence in block: {ev.hash().hex()}")
            seen.add(ev.hash())

    # -- update on commit ---------------------------------------------------

    def update(self, state, evidence: List[Evidence]) -> None:
        """Mark committed, flush consensus buffer, prune expired (pool.go:105)."""
        with self._mtx:
            self.state = state
            # mark committed + remove from pending
            sets, deletes = [], []
            for ev in evidence:
                sets.append((_key(_COMMITTED_PREFIX, ev),
                             ev.height().to_bytes(8, "big")))
                deletes.append(_key(_PENDING_PREFIX, ev))
            if sets or deletes:
                self._db.write_batch(sets, deletes)
            # flush buffered conflicting votes into real evidence
            buffered, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buffered:
            self._process_conflicting_votes(vote_a, vote_b)
        self._prune_expired()

    def _process_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        val_set = self.state_store.load_validators(vote_a.height)
        if val_set is None:
            logger.error("no validator set at height %d for conflicting votes",
                         vote_a.height)
            return
        block_meta = self.block_store.load_block_meta(vote_a.height)
        if block_meta is None:
            logger.error("no block meta at height %d for conflicting votes",
                         vote_a.height)
            return
        ev = DuplicateVoteEvidence.new(vote_a, vote_b, block_meta.header.time_ns,
                                       val_set)
        if ev is None:
            return
        try:
            self.add_evidence(ev)
        except ValueError as e:
            logger.error("failed to add duplicate-vote evidence: %s", e)

    def _prune_expired(self) -> None:
        """(pool.go:450 removeExpiredPendingEvidence)"""
        if self.state is None:
            return
        params = self.state.consensus_params.evidence
        height = self.state.last_block_height
        now = self.state.last_block_time_ns
        deletes = []
        for k, v in self._db.iterate_prefix(_PENDING_PREFIX):
            ev = decode_evidence(v)
            expired_blocks = ev.height() + params.max_age_num_blocks < height
            expired_time = ev.time_ns() + params.max_age_duration_ns < now
            if expired_blocks and expired_time:
                deletes.append(k)
        if deletes:
            self._db.write_batch([], deletes)

    def abci_evidence(self, evidence: List[Evidence]):
        from ..state.execution import ev_to_abci

        return [ev_to_abci(ev) for ev in evidence]
