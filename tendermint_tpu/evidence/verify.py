"""Evidence verification (reference evidence/verify.go).

Duplicate-vote: both signatures checked in ONE batched device call instead of
two scalar verifies (verify.go:214,217 — a batch-offload site from SURVEY.md).
"""

from __future__ import annotations

from ..crypto.batch import BatchVerifier
from ..types import DuplicateVoteEvidence, Evidence, LightClientAttackEvidence
from ..types.validator_set import ValidatorSet

DEFAULT_TRUST_LEVEL = (1, 3)  # light.DefaultTrustLevel


class ErrNoEvidenceData(Exception):
    """We lack the header/valset to judge this evidence (benign: we may be
    behind or pruned) — callers must NOT punish the sender for it."""


def verify_duplicate_vote(e: DuplicateVoteEvidence, chain_id: str,
                          val_set: ValidatorSet) -> None:
    """(verify.go:162)"""
    _, val = val_set.get_by_address(e.vote_a.validator_address)
    if val is None:
        raise ValueError(
            f"address {e.vote_a.validator_address.hex().upper()} was not a validator "
            f"at height {e.height()}")
    pub_key = val.pub_key

    if (e.vote_a.height != e.vote_b.height or e.vote_a.round != e.vote_b.round
            or e.vote_a.type != e.vote_b.type):
        raise ValueError(
            f"h/r/s does not match: {e.vote_a.height}/{e.vote_a.round}/{e.vote_a.type} "
            f"vs {e.vote_b.height}/{e.vote_b.round}/{e.vote_b.type}")
    if e.vote_a.validator_address != e.vote_b.validator_address:
        raise ValueError(
            f"validator addresses do not match: {e.vote_a.validator_address.hex()} "
            f"vs {e.vote_b.validator_address.hex()}")
    if e.vote_a.block_id == e.vote_b.block_id:
        raise ValueError(
            f"block IDs are the same ({e.vote_a.block_id}) - not a real duplicate vote")
    if pub_key.address() != e.vote_a.validator_address:
        raise ValueError("address doesn't match pubkey")
    if val.voting_power != e.validator_power:
        raise ValueError(
            f"validator power from evidence and our validator set does not match "
            f"({e.validator_power} != {val.voting_power})")
    if val_set.total_voting_power() != e.total_voting_power:
        raise ValueError(
            f"total voting power from the evidence and our validator set does not "
            f"match ({e.total_voting_power} != {val_set.total_voting_power()})")

    # Both signatures in one device batch (verify.go:214,217).
    bv = BatchVerifier(plane="evidence")
    bv.add(pub_key, e.vote_a.sign_bytes(chain_id), e.vote_a.signature)
    bv.add(pub_key, e.vote_b.sign_bytes(chain_id), e.vote_b.signature)
    _, per_item = bv.verify()
    if not per_item[0]:
        raise ValueError("verifying VoteA: invalid signature")
    if not per_item[1]:
        raise ValueError("verifying VoteB: invalid signature")


def verify_light_client_attack(e: LightClientAttackEvidence, chain_id: str,
                               common_header, trusted_header,
                               common_vals: ValidatorSet) -> None:
    """(verify.go:113) — simplified: byzantine-validator recomputation checks
    happen in the pool once the light client lands (SURVEY.md stage 9)."""
    cb = e.conflicting_block
    if common_header.height != cb.height:
        # commit_vals: aggregated commits pair against the conflicting
        # block's own set (the bitmap indexes it); plain commits ignore it
        common_vals.verify_commit_light_trusting(
            chain_id, cb.signed_header.commit, DEFAULT_TRUST_LEVEL,
            commit_vals=cb.validator_set)
    elif cb.signed_header.header.hash() != cb.signed_header.commit.block_id.hash:
        raise ValueError(
            "common height is the same as conflicting block height so expected the "
            "conflicting block to be correctly derived yet it wasn't")
    cb.validator_set.verify_commit_light(
        chain_id, cb.signed_header.commit.block_id, cb.height,
        cb.signed_header.commit)
    if e.total_voting_power != common_vals.total_voting_power():
        raise ValueError(
            f"total voting power from the evidence and our validator set does not "
            f"match ({e.total_voting_power} != {common_vals.total_voting_power()})")
    if (cb.height > trusted_header.height
            and cb.signed_header.header.time_ns > trusted_header.time_ns):
        raise ValueError("conflicting block doesn't violate monotonically increasing time")
    if (cb.height <= trusted_header.height
            and trusted_header.hash() == cb.signed_header.header.hash()):
        raise ValueError("trusted header hash matches the evidence's conflicting header hash")


def verify_evidence(ev: Evidence, state, state_store, block_store) -> None:
    """Entry check against node state (verify.go:37 verify)."""
    height = state.last_block_height
    ev_height = ev.height()
    age_num_blocks = height - ev_height
    params = state.consensus_params.evidence

    block_meta = block_store.load_block_meta(ev_height)
    if block_meta is None:
        raise ErrNoEvidenceData(f"don't have header at height #{ev_height}")
    ev_time = block_meta.header.time_ns
    age_duration = state.last_block_time_ns - ev_time
    if age_duration > params.max_age_duration_ns and age_num_blocks > params.max_age_num_blocks:
        raise ValueError(
            f"evidence from height {ev_height} is too old; min height is "
            f"{height - params.max_age_num_blocks}")

    if isinstance(ev, DuplicateVoteEvidence):
        val_set = state_store.load_validators(ev_height)
        if val_set is None:
            raise ErrNoEvidenceData(f"no validator set at height {ev_height}")
        verify_duplicate_vote(ev, state.chain_id, val_set)
        if ev.timestamp_ns != ev_time:
            raise ValueError(
                f"evidence has a different time to the block it is associated with "
                f"({ev.timestamp_ns} != {ev_time})")
    elif isinstance(ev, LightClientAttackEvidence):
        common_vals = state_store.load_validators(ev.common_height)
        if common_vals is None:
            raise ErrNoEvidenceData(f"no validator set at height {ev.common_height}")
        common_meta = block_store.load_block_meta(ev.common_height)
        if common_meta is None:
            raise ErrNoEvidenceData(f"don't have header at height #{ev.common_height}")
        trusted_meta = block_store.load_block_meta(ev.conflicting_block.height)
        if trusted_meta is None:
            trusted_meta = block_store.load_block_meta(block_store.height())
        if trusted_meta is None:
            raise ErrNoEvidenceData("no trusted header available")
        verify_light_client_attack(ev, state.chain_id, common_meta.header,
                                   trusted_meta.header, common_vals)
    else:
        raise ValueError(f"unrecognized evidence type: {type(ev)}")
