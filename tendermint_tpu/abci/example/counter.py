"""counter example app (reference abci/example/counter/counter.go).

In serial mode, txs must be the big-endian encoding of the current tx count
— CheckTx rejects txs <= the committed count, DeliverTx requires exactly
count+1. Used pervasively by the reference's consensus tests to detect
reordering/replay.
"""

from __future__ import annotations

from .. import types as abci
from ..application import Application


class CounterApplication(Application):
    def __init__(self, serial: bool = True):
        self.serial = serial
        self.tx_count = 0
        self.height = 0

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f"{{\"hashes\":{self.height},\"txs\":{self.tx_count}}}",
            last_block_height=self.height,
            last_block_app_hash=self._hash(),
        )

    def _hash(self) -> bytes:
        if self.tx_count == 0:
            return b""
        return self.tx_count.to_bytes(8, "big")

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self.serial:
            if len(req.tx) > 8:
                return abci.ResponseCheckTx(
                    code=1, log=f"max tx size is 8 bytes, got {len(req.tx)}")
            value = int.from_bytes(req.tx, "big")
            if value < self.tx_count:
                return abci.ResponseCheckTx(
                    code=2, log=f"invalid nonce: got {value}, expected >= "
                                f"{self.tx_count}")
        return abci.ResponseCheckTx(code=0)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if self.serial:
            if len(req.tx) > 8:
                return abci.ResponseDeliverTx(
                    code=1, log=f"max tx size is 8 bytes, got {len(req.tx)}")
            value = int.from_bytes(req.tx, "big")
            if value != self.tx_count:
                return abci.ResponseDeliverTx(
                    code=2, log=f"invalid nonce: got {value}, expected "
                                f"{self.tx_count}")
        self.tx_count += 1
        return abci.ResponseDeliverTx(code=0)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "hash":
            return abci.ResponseQuery(code=0, value=str(self.height).encode())
        if req.path == "tx":
            return abci.ResponseQuery(code=0, value=str(self.tx_count).encode())
        return abci.ResponseQuery(code=1, log=f"invalid query path {req.path}")

    def commit(self) -> abci.ResponseCommit:
        self.height += 1
        return abci.ResponseCommit(data=self._hash())
