"""kvstore example app (reference abci/example/kvstore/).

Txs are "key=value" (or raw bytes stored under themselves). The persistent
variant accepts validator-update txs: "val:<pubkey_hex>!<power>" — mirroring
the reference's persistent_kvstore (abci/example/kvstore/persistent_kvstore.go).
State hash = big-endian tx count (kvstore.go State.Hash semantics: size-based
deterministic app hash).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional

from .. import types as abci
from ..application import Application

VALIDATOR_TX_PREFIX = "val:"


class KVStoreApplication(Application):
    #: speculation protocol below (spec_read / deliver_tx_on_view /
    #: apply_spec_ops) — see abci/application.py for the contract
    parallel_exec_supported = True

    def __init__(self):
        self.state: Dict[str, str] = {}
        self.tx_count = 0  # deterministic state size counter
        self.height = 0
        self.app_hash = b""
        self.val_updates: List[abci.ValidatorUpdate] = []
        self.validators: Dict[str, int] = {}  # pubkey hex -> power

    # -- info --
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": self.tx_count}),
            version="0.1.0",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/store" or req.path == "":
            key = req.data.decode("utf-8", errors="replace")
            val = self.state.get(key)
            if val is None:
                return abci.ResponseQuery(code=0, key=req.data, log="does not exist",
                                          height=self.height)
            return abci.ResponseQuery(code=0, key=req.data, value=val.encode(),
                                      log="exists", height=self.height)
        if req.path == "/val":
            power = self.validators.get(req.data.decode(), 0)
            return abci.ResponseQuery(code=0, key=req.data,
                                      value=str(power).encode(), height=self.height)
        return abci.ResponseQuery(code=1, log=f"unknown path {req.path}")

    # -- mempool --
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if tx_is_validator_update(req.tx) and parse_validator_tx(req.tx) is None:
            return abci.ResponseCheckTx(code=1, log="malformed validator tx")
        return abci.ResponseCheckTx(code=0, gas_wanted=1)

    # -- consensus --
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self.validators[vu.pub_key_bytes.hex()] = vu.power
        return abci.ResponseInitChain()

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        self.val_updates = []
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if tx_is_validator_update(req.tx):
            parsed = parse_validator_tx(req.tx)
            if parsed is None:
                return abci.ResponseDeliverTx(code=1, log="malformed validator tx")
            pubkey_hex, power = parsed
            self.validators[pubkey_hex] = power
            self.val_updates.append(abci.ValidatorUpdate(
                pub_key_type="ed25519", pub_key_bytes=bytes.fromhex(pubkey_hex), power=power))
        else:
            raw = req.tx.decode("utf-8", errors="replace")
            if "=" in raw:
                k, v = raw.split("=", 1)
            else:
                k = v = raw
            self._set_kv(k, v)
        self.tx_count += 1
        return abci.ResponseDeliverTx(code=0, events=_tx_events(req.tx),
                                      gas_wanted=1, gas_used=1)

    def _set_kv(self, k: str, v: str, vhash: Optional[bytes] = None) -> None:
        """Single store-write seam: MerkleKVStoreApplication hooks it for
        value-hash caching + dirty-leaf tracking. ``vhash`` is sha256(v)
        when the caller already computed it (the speculative path hashes
        in parallel worker threads), else recomputed where needed."""
        self.state[k] = v

    # -- optimistic parallel execution (state/parallel.py) -----------------

    def spec_read(self, space: str, key: str):
        if space == "kv":
            return self.state.get(key)
        if space == "val":
            return self.validators.get(key)
        return None

    def deliver_tx_on_view(self, tx: bytes, view) -> abci.ResponseDeliverTx:
        """deliver_tx's speculation twin: same decision logic and response
        bytes, state effects recorded on the view instead of applied.
        Value hashing happens HERE — in the speculating worker thread,
        where hashlib releases the GIL for large values — so the serial
        apply/commit path never recomputes it."""
        if tx_is_validator_update(tx):
            parsed = parse_validator_tx(tx)
            if parsed is None:
                return abci.ResponseDeliverTx(code=1,
                                              log="malformed validator tx")
            pubkey_hex, power = parsed
            view.write("val", pubkey_hex, power)
            # shared ordered stream: cross-group validator updates always
            # conflict, so mixed-order val_updates are impossible
            view.emit("vup", (pubkey_hex, power))
        else:
            raw = tx.decode("utf-8", errors="replace")
            if "=" in raw:
                k, v = raw.split("=", 1)
            else:
                k = v = raw
            view.write("kv", k, v, extra=hashlib.sha256(v.encode()).digest())
        view.add("tx_count", 1)
        return abci.ResponseDeliverTx(code=0, events=_tx_events(tx),
                                      gas_wanted=1, gas_used=1)

    def apply_spec_ops(self, ops) -> None:
        for op in ops:
            kind = op[0]
            if kind == "set":
                _, space, key, value, extra = op
                if space == "kv":
                    self._set_kv(key, value, extra)
                else:  # "val"
                    self.validators[key] = value
            elif kind == "emit":  # ("emit", "vup", (pubkey_hex, power))
                pubkey_hex, power = op[2]
                self.val_updates.append(abci.ValidatorUpdate(
                    pub_key_type="ed25519",
                    pub_key_bytes=bytes.fromhex(pubkey_hex), power=power))
            else:  # ("add", "tx_count", n)
                self.tx_count += op[2]

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock(validator_updates=list(self.val_updates))

    def commit(self) -> abci.ResponseCommit:
        self.height += 1
        self.app_hash = self.tx_count.to_bytes(8, "big")
        return abci.ResponseCommit(data=self.app_hash)


class SnapshotKVStoreApplication(KVStoreApplication):
    """kvstore + state-sync snapshots (the reference's e2e app shape,
    test/e2e/app/snapshots.go): every ``interval`` heights the full app state
    is serialized to JSON and split into fixed-size chunks."""

    CHUNK_SIZE = 1024

    def __init__(self, interval: int = 4):
        super().__init__()
        self.snapshot_interval = interval
        self._snapshots: Dict[int, List[bytes]] = {}  # height -> chunks
        self._restore: Optional[Dict] = None

    def commit(self) -> abci.ResponseCommit:
        resp = super().commit()
        if self.snapshot_interval and self.height % self.snapshot_interval == 0:
            blob = json.dumps({
                "state": self.state, "tx_count": self.tx_count,
                "height": self.height, "validators": self.validators,
            }, sort_keys=True).encode()
            chunks = [blob[i:i + self.CHUNK_SIZE]
                      for i in range(0, max(len(blob), 1), self.CHUNK_SIZE)]
            self._snapshots[self.height] = chunks
        return resp

    def list_snapshots(self, req: abci.RequestListSnapshots
                       ) -> abci.ResponseListSnapshots:
        out = []
        for h, chunks in sorted(self._snapshots.items()):
            # metadata carries per-chunk hashes (the reference e2e app's
            # trick): restore can then verify EACH chunk as it arrives and
            # blame the specific sender of a corrupted one, instead of
            # discovering a whole-blob mismatch at the end with no culprit
            meta = json.dumps({"chunk_hashes": [
                hashlib.sha256(c).hexdigest() for c in chunks]}).encode()
            out.append(abci.Snapshot(
                height=h, format=1, chunks=len(chunks),
                hash=hashlib.sha256(b"".join(chunks)).digest(),
                metadata=meta))
        return abci.ResponseListSnapshots(snapshots=out)

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk
                            ) -> abci.ResponseLoadSnapshotChunk:
        chunks = self._snapshots.get(req.height)
        if req.format != 1 or chunks is None or not 0 <= req.chunk < len(chunks):
            return abci.ResponseLoadSnapshotChunk(chunk=b"")
        return abci.ResponseLoadSnapshotChunk(chunk=chunks[req.chunk])

    def offer_snapshot(self, req: abci.RequestOfferSnapshot
                       ) -> abci.ResponseOfferSnapshot:
        if req.snapshot is None or req.snapshot.format != 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restore = {"snapshot": req.snapshot, "app_hash": req.app_hash,
                         "chunks": [],
                         # parsed once here: apply_snapshot_chunk runs per
                         # chunk and must not re-decode an O(chunks) list
                         "chunk_hashes": _parse_chunk_hashes(req.snapshot)}
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk
                             ) -> abci.ResponseApplySnapshotChunk:
        if self._restore is None:
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT)
        expected = self._restore["chunk_hashes"]
        if (expected is not None
                and hashlib.sha256(req.chunk).hexdigest()
                != expected[req.index]):
            # corrupted chunk from an untrusted peer: don't apply it — ask
            # for a refetch and name the sender so the syncer can ban it
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_RETRY,
                refetch_chunks=[req.index],
                reject_senders=[req.sender] if req.sender else [])
        self._restore["chunks"].append(req.chunk)
        snap = self._restore["snapshot"]
        if len(self._restore["chunks"]) == snap.chunks:
            blob = b"".join(self._restore["chunks"])
            if hashlib.sha256(blob).digest() != snap.hash:
                self._restore = None
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT)
            doc = json.loads(blob)
            self.state = dict(doc["state"])
            self.tx_count = doc["tx_count"]
            self.height = doc["height"]
            self.validators = dict(doc["validators"])
            self.app_hash = self.tx_count.to_bytes(8, "big")
            self._restore = None
        return abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_SNAPSHOT_CHUNK_ACCEPT)


def _parse_chunk_hashes(snap: abci.Snapshot) -> Optional[List[str]]:
    """Per-chunk sha256 hexdigests from a snapshot's metadata; None when
    absent/garbled (older snapshots, or a lying advertiser — the final
    whole-blob check still guards those)."""
    try:
        hashes = json.loads(snap.metadata.decode())["chunk_hashes"]
    except Exception:
        return None
    if (not isinstance(hashes, list) or len(hashes) != snap.chunks
            or not all(isinstance(x, str) for x in hashes)):
        return None
    return hashes


def _tx_events(tx: bytes) -> List[abci.Event]:
    return [abci.Event(type="app", attributes=[
        abci.EventAttribute(b"creator", b"tendermint_tpu", True),
        abci.EventAttribute(b"key", tx.split(b"=", 1)[0], True),
    ])]


def tx_is_validator_update(tx: bytes) -> bool:
    return tx.decode("utf-8", errors="replace").startswith(VALIDATOR_TX_PREFIX)


def parse_validator_tx(tx: bytes) -> "Optional[tuple[str, int]]":
    try:
        body = tx.decode("utf-8")[len(VALIDATOR_TX_PREFIX):]
        pubkey_hex, power_s = body.split("!", 1)
        bytes.fromhex(pubkey_hex)
        power = int(power_s)
        if power < 0 or len(bytes.fromhex(pubkey_hex)) != 32:
            return None
        return pubkey_hex, power
    except (ValueError, UnicodeDecodeError):
        return None


class MerkleKVStoreApplication(SnapshotKVStoreApplication):
    """kvstore whose app hash is an RFC-6962 merkle root over the sorted
    key/value state, serving merkle ``ProofOps`` on ``Query(prove=True)`` —
    the proof path the reference's light proxy verifies queries with
    (light/rpc/client.go ABCIQueryWithOptions → merkle.ProofRuntime;
    leaf encoding per crypto/merkle/proof_value.go ValueOp).

    The proof at query height H verifies against the app hash carried in
    HEADER H+1 (AppHash(H+1) = Commit(H) result), exactly the reference's
    height convention.

    Commit cost: the root is maintained by crypto.merkle.IncrementalMerkle
    — only leaves whose value changed since the last commit re-hash
    (``_dirty``), the level reduce vectorizes through the crypto plane's
    batched SHA-256 when the tree is large, and ``TMTPU_MERKLE_FAST=0``
    forces the recursive spec recompute (byte-identical by construction
    and by differential test).
    """

    def __init__(self, interval: int = 4):
        super().__init__(interval)
        self._vhash: Dict[str, bytes] = {}  # key -> sha256(value)
        self._dirty: set = set()            # keys written since last commit
        from ...crypto.merkle import IncrementalMerkle

        self._imt = IncrementalMerkle()

    def _set_kv(self, k: str, v: str, vhash: Optional[bytes] = None) -> None:
        self.state[k] = v
        self._vhash[k] = vhash if vhash is not None \
            else hashlib.sha256(v.encode()).digest()
        self._dirty.add(k)

    def _leaf_item(self, k: str) -> bytes:
        from ...crypto.merkle import _encode_byte_slice

        vh = self._vhash.get(k)
        if vh is None:  # state poked behind _set_kv (tests, tools)
            vh = hashlib.sha256(self.state[k].encode()).digest()
            self._vhash[k] = vh
        return (_encode_byte_slice(k.encode())
                + _encode_byte_slice(vh))

    @staticmethod
    def _leaf_items(state: Dict[str, str]) -> List[bytes]:
        """The SPEC leaf encoding (proof_value.go ValueOp), recomputed
        from scratch — the incremental path must match it byte-for-byte."""
        from ...crypto.merkle import _encode_byte_slice

        items = []
        for k in sorted(state):
            vhash = hashlib.sha256(state[k].encode()).digest()
            items.append(_encode_byte_slice(k.encode())
                         + _encode_byte_slice(vhash))
        return items

    def _reset_merkle_cache(self) -> None:
        """Rebuild value-hash cache + drop the level cache (snapshot
        restore and any other out-of-band state swap)."""
        self._vhash = {k: hashlib.sha256(v.encode()).digest()
                       for k, v in self.state.items()}
        self._dirty = set()
        self._imt.reset()

    def commit(self) -> abci.ResponseCommit:
        import os

        resp = super().commit()
        if os.environ.get("TMTPU_MERKLE_FAST", "1") == "0":
            from ...crypto.merkle import hash_from_byte_slices

            self.app_hash = hash_from_byte_slices(
                self._leaf_items(self.state))
        else:
            self.app_hash = self._imt.root(sorted(self.state),
                                           self._leaf_item, self._dirty)
        self._dirty = set()
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        resp = super().query(req)
        # proofs exist only for the KV store path; /val and missing keys
        # answer unproven (the light proxy then refuses to vouch for them)
        key = req.data.decode("utf-8", errors="replace")
        # queries run on their own connection lock (proxy.py) and may
        # interleave with a block mid-apply: take one atomic snapshot of
        # the store instead of iterating the live dict
        snap = dict(self.state)
        if (req.prove and resp.code == 0 and resp.value
                and req.path in ("", "/store") and key in snap):
            from ...crypto.merkle import (
                ProofOp,
                ValueOp,
                proofs_from_byte_slices,
            )

            idx = sorted(snap).index(key)
            proof = proofs_from_byte_slices(self._leaf_items(snap))[idx]
            op = ValueOp(req.data, proof).proof_op()
            resp.proof_ops = [ProofOp(op.type, op.key, op.data)]
        return resp

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk
                             ) -> abci.ResponseApplySnapshotChunk:
        from ...crypto.merkle import hash_from_byte_slices

        resp = super().apply_snapshot_chunk(req)
        if (self._restore is None
                and resp.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT):
            # restore completed: the app hash is the merkle root, not the
            # parent's tx-count encoding; the incremental cache is stale
            self._reset_merkle_cache()
            self.app_hash = hash_from_byte_slices(self._leaf_items(self.state))
        return resp
