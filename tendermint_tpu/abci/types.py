"""ABCI request/response types (reference abci/types/types.pb.go).

Dataclasses with JSON (storage) and — where consensus requires byte parity —
protobuf encoding: deterministic ResponseDeliverTx feeds LastResultsHash
(reference types/results.go), so its proto encoding matches gogo exactly.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ..libs import protowire as pw

CODE_TYPE_OK = 0


# --- events ----------------------------------------------------------------

@dataclass
class EventAttribute:
    key: bytes = b""
    value: bytes = b""
    index: bool = False


@dataclass
class Event:
    type: str = ""
    attributes: List[EventAttribute] = field(default_factory=list)


# --- validators ------------------------------------------------------------

@dataclass
class ValidatorUpdate:
    pub_key_type: str = "ed25519"
    pub_key_bytes: bytes = b""
    power: int = 0
    # bls12381 keys must arrive with a proof of possession — the rogue-key
    # gate validate_validator_updates enforces before admission; unused for
    # every other scheme
    pop: bytes = b""


@dataclass
class ABCIValidator:
    """abci.Validator: address + power (in LastCommitInfo / evidence)."""

    address: bytes = b""
    power: int = 0


@dataclass
class VoteInfo:
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    signed_last_block: bool = False


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: List[VoteInfo] = field(default_factory=list)


@dataclass
class ABCIEvidence:
    type: str = ""  # DUPLICATE_VOTE | LIGHT_CLIENT_ATTACK
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    height: int = 0
    time_ns: int = 0
    total_voting_power: int = 0


# --- param updates ---------------------------------------------------------

@dataclass
class ABCIBlockParams:
    max_bytes: int = 0
    max_gas: int = 0


@dataclass
class ABCIEvidenceParams:
    max_age_num_blocks: int = 0
    max_age_duration_ns: int = 0
    max_bytes: int = 0


@dataclass
class ABCIValidatorParams:
    pub_key_types: List[str] = field(default_factory=list)


@dataclass
class ABCIVersionParams:
    app_version: int = 0


@dataclass
class ABCIConsensusParams:
    block: Optional[ABCIBlockParams] = None
    evidence: Optional[ABCIEvidenceParams] = None
    validator: Optional[ABCIValidatorParams] = None
    version: Optional[ABCIVersionParams] = None


# --- requests --------------------------------------------------------------

@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class RequestInitChain:
    time_ns: int = 0
    chain_id: str = ""
    consensus_params: Optional[ABCIConsensusParams] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 0


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: object = None  # types.Header
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: List[ABCIEvidence] = field(default_factory=list)


CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class RequestOfferSnapshot:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


# --- responses -------------------------------------------------------------

@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseInitChain:
    consensus_params: Optional[ABCIConsensusParams] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: Optional[object] = None
    height: int = 0
    codespace: str = ""


@dataclass
class ResponseBeginBlock:
    events: List[Event] = field(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def deterministic_encode(self) -> bytes:
        """Proto encoding of the deterministic subset {code,data,gas_wanted,
        gas_used} — merkle leaf of LastResultsHash (types/results.go:45)."""
        w = pw.Writer()
        w.varint(1, self.code)
        w.bytes(2, self.data)
        w.varint(5, self.gas_wanted)
        w.varint(6, self.gas_used)
        return w.finish()


@dataclass
class ResponseEndBlock:
    validator_updates: List[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[ABCIConsensusParams] = None
    events: List[Event] = field(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: List[Snapshot] = field(default_factory=list)


OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_REJECT


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


APPLY_SNAPSHOT_CHUNK_ACCEPT = 1
APPLY_SNAPSHOT_CHUNK_ABORT = 2
APPLY_SNAPSHOT_CHUNK_RETRY = 3
APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT = 4
APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT = 5


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT
    refetch_chunks: List[int] = field(default_factory=list)
    reject_senders: List[str] = field(default_factory=list)


def last_results_hash(deliver_txs: List[ResponseDeliverTx]) -> bytes:
    """Merkle root over deterministic DeliverTx encodings (types/results.go:22)."""
    from ..crypto import merkle

    return merkle.hash_from_byte_slices([r.deterministic_encode() for r in deliver_txs])
