"""abci-cli: exercise an ABCI app over its socket
(reference abci/cmd/abci-cli — echo, info, deliver_tx, check_tx, commit,
query, plus a console mode).

Usage:
    python -m tendermint_tpu.abci.cli --address tcp://127.0.0.1:26658 info
    python -m tendermint_tpu.abci.cli deliver_tx 0x6b3d76   # or "k=v"
    python -m tendermint_tpu.abci.cli console
"""

from __future__ import annotations

import argparse
import sys

from . import types as abci
from .client import SocketClient


def _parse_bytes(s: str) -> bytes:
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    return s.encode()


def run_command(client: SocketClient, cmd: str, args) -> int:
    if cmd == "echo":
        print(client.echo(args[0] if args else ""))
    elif cmd == "info":
        r = client.info(abci.RequestInfo())
        print(f"-> data: {r.data}\n-> last_block_height: {r.last_block_height}"
              f"\n-> last_block_app_hash: 0x{r.last_block_app_hash.hex()}")
    elif cmd == "deliver_tx":
        r = client.deliver_tx(abci.RequestDeliverTx(tx=_parse_bytes(args[0])))
        print(f"-> code: {r.code}\n-> data: 0x{r.data.hex()}\n-> log: {r.log}")
    elif cmd == "check_tx":
        r = client.check_tx(abci.RequestCheckTx(tx=_parse_bytes(args[0])))
        print(f"-> code: {r.code}\n-> log: {r.log}")
    elif cmd == "commit":
        r = client.commit()
        print(f"-> data: 0x{r.data.hex()}")
    elif cmd == "query":
        r = client.query(abci.RequestQuery(data=_parse_bytes(args[0])))
        print(f"-> code: {r.code}\n-> key: {r.key.decode(errors='replace')}"
              f"\n-> value: {r.value.decode(errors='replace')}\n-> log: {r.log}")
    else:
        print(f"unknown command {cmd!r}", file=sys.stderr)
        return 1
    return 0


def console(client: SocketClient) -> int:
    print("> type a command (echo/info/deliver_tx/check_tx/commit/query), "
          "ctrl-d to exit")
    while True:
        try:
            line = input("> ").strip()
        except EOFError:
            return 0
        if not line:
            continue
        parts = line.split()
        try:
            run_command(client, parts[0], parts[1:])
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="abci-cli")
    p.add_argument("--address", default="tcp://127.0.0.1:26658")
    p.add_argument("--transport", default="socket", choices=("socket", "grpc"))
    p.add_argument("command", choices=["echo", "info", "deliver_tx",
                                       "check_tx", "commit", "query",
                                       "console"])
    p.add_argument("args", nargs="*")
    ns = p.parse_args(argv)
    if ns.transport == "grpc":
        from .grpc import GrpcClient

        client = GrpcClient(ns.address)
    else:
        client = SocketClient(ns.address)
    try:
        if ns.command == "console":
            return console(client)
        return run_command(client, ns.command, ns.args)
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
