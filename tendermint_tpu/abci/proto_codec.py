"""ABCI protobuf wire codec (reference proto/tendermint/abci/types.proto +
abci/client/socket_client.go:27 framing).

Encodes/decodes the Request/Response oneof envelopes with the exact gogoproto
field numbers, framed as uvarint-length-delimited messages (libs/protoio) —
so reference-compatible out-of-process ABCI apps can attach to this node's
socket client, and reference nodes can drive apps served by our server.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ..libs import protowire as pw
from . import types as abci

# oneof field numbers (types.proto:23-38 / :131-148)
REQ_FIELDS = {
    "echo": 1, "flush": 2, "info": 3, "set_option": 4, "init_chain": 5,
    "query": 6, "begin_block": 7, "check_tx": 8, "deliver_tx": 9,
    "end_block": 10, "commit": 11, "list_snapshots": 12, "offer_snapshot": 13,
    "load_snapshot_chunk": 14, "apply_snapshot_chunk": 15,
}
REQ_BY_FIELD = {v: k for k, v in REQ_FIELDS.items()}
RESP_FIELDS = {
    "exception": 1, "echo": 2, "flush": 3, "info": 4, "set_option": 5,
    "init_chain": 6, "query": 7, "begin_block": 8, "check_tx": 9,
    "deliver_tx": 10, "end_block": 11, "commit": 12, "list_snapshots": 13,
    "offer_snapshot": 14, "load_snapshot_chunk": 15,
    "apply_snapshot_chunk": 16,
}
RESP_BY_FIELD = {v: k for k, v in RESP_FIELDS.items()}

_EVIDENCE_TYPES = {"": 0, "UNKNOWN": 0, "DUPLICATE_VOTE": 1,
                   "LIGHT_CLIENT_ATTACK": 2}
_EVIDENCE_NAMES = {v: k for k, v in _EVIDENCE_TYPES.items() if k}
_EVIDENCE_NAMES[0] = "UNKNOWN"


# --- shared sub-messages ----------------------------------------------------

def _enc_event(ev: abci.Event) -> bytes:
    w = pw.Writer()
    w.string(1, ev.type)
    for a in ev.attributes:
        aw = pw.Writer()
        aw.bytes(1, a.key)
        aw.bytes(2, a.value)
        if a.index:
            aw.bool(3, True)
        w.message(2, aw.finish())
    return w.finish()


def _dec_event(body: bytes) -> abci.Event:
    ev = abci.Event()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            ev.type = v.decode()
        elif fn == 2:
            a = abci.EventAttribute()
            for afn, _awt, av in pw.iter_fields(v):
                if afn == 1:
                    a.key = av
                elif afn == 2:
                    a.value = av
                elif afn == 3:
                    a.index = bool(av)
            ev.attributes.append(a)
    return ev


def _enc_validator(v: abci.ABCIValidator) -> bytes:
    w = pw.Writer()
    w.bytes(1, v.address)
    w.varint(3, v.power)
    return w.finish()


def _dec_validator(body: bytes) -> abci.ABCIValidator:
    out = abci.ABCIValidator()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            out.address = v
        elif fn == 3:
            out.power = pw.varint_to_int64(v)
    return out


_PUBKEY_TYPE_TO_FIELD = {"ed25519": 1, "secp256k1": 2, "bls12381": 3}
_PUBKEY_FIELD_TO_TYPE = {f: t for t, f in _PUBKEY_TYPE_TO_FIELD.items()}


def _enc_validator_update(vu: abci.ValidatorUpdate) -> bytes:
    pk = pw.Writer()
    pk.bytes(_PUBKEY_TYPE_TO_FIELD.get(vu.pub_key_type, 2), vu.pub_key_bytes)
    w = pw.Writer()
    w.message(1, pk.finish())
    w.varint(2, vu.power)
    if vu.pop:  # bls12381 proof of possession; absent elsewhere
        w.bytes(3, vu.pop)
    return w.finish()


def _dec_validator_update(body: bytes) -> abci.ValidatorUpdate:
    out = abci.ValidatorUpdate()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            for pfn, _pwt, pv in pw.iter_fields(v):
                out.pub_key_type = _PUBKEY_FIELD_TO_TYPE.get(pfn, "secp256k1")
                out.pub_key_bytes = pv
        elif fn == 2:
            out.power = pw.varint_to_int64(v)
        elif fn == 3:
            out.pop = v
    return out


def _enc_last_commit_info(lci: abci.LastCommitInfo) -> bytes:
    w = pw.Writer()
    if lci.round:
        w.varint(1, lci.round)
    for vi in lci.votes:
        vw = pw.Writer()
        vw.message(1, _enc_validator(vi.validator))
        if vi.signed_last_block:
            vw.bool(2, True)
        w.message(2, vw.finish())
    return w.finish()


def _dec_last_commit_info(body: bytes) -> abci.LastCommitInfo:
    out = abci.LastCommitInfo()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            out.round = pw.varint_to_int64(v)
        elif fn == 2:
            vi = abci.VoteInfo()
            for vfn, _vwt, vv in pw.iter_fields(v):
                if vfn == 1:
                    vi.validator = _dec_validator(vv)
                elif vfn == 2:
                    vi.signed_last_block = bool(vv)
            out.votes.append(vi)
    return out


def _enc_evidence(e: abci.ABCIEvidence) -> bytes:
    w = pw.Writer()
    t = _EVIDENCE_TYPES.get(e.type, 0)
    if t:
        w.varint(1, t)
    w.message(2, _enc_validator(e.validator))
    if e.height:
        w.varint(3, e.height)
    w.message(4, _ts_body(e.time_ns))
    if e.total_voting_power:
        w.varint(5, e.total_voting_power)
    return w.finish()


def _ts_body(ns: int) -> bytes:
    w = pw.Writer()
    secs, nanos = divmod(ns, 1_000_000_000)
    if secs:
        w.varint(1, secs)
    if nanos:
        w.varint(2, nanos)
    return w.finish()


def _dec_ts(body: bytes) -> int:
    return pw.parse_timestamp(body)


def _dec_evidence(body: bytes) -> abci.ABCIEvidence:
    out = abci.ABCIEvidence()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            out.type = _EVIDENCE_NAMES.get(pw.varint_to_int64(v), "UNKNOWN")
        elif fn == 2:
            out.validator = _dec_validator(v)
        elif fn == 3:
            out.height = pw.varint_to_int64(v)
        elif fn == 4:
            out.time_ns = _dec_ts(v)
        elif fn == 5:
            out.total_voting_power = pw.varint_to_int64(v)
    return out


def _enc_snapshot(s: abci.Snapshot) -> bytes:
    w = pw.Writer()
    if s.height:
        w.varint(1, s.height)
    if s.format:
        w.varint(2, s.format)
    if s.chunks:
        w.varint(3, s.chunks)
    if s.hash:
        w.bytes(4, s.hash)
    if s.metadata:
        w.bytes(5, s.metadata)
    return w.finish()


def _dec_snapshot(body: bytes) -> abci.Snapshot:
    out = abci.Snapshot()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            out.height = pw.varint_to_int64(v)
        elif fn == 2:
            out.format = pw.varint_to_int64(v)
        elif fn == 3:
            out.chunks = pw.varint_to_int64(v)
        elif fn == 4:
            out.hash = v
        elif fn == 5:
            out.metadata = v
    return out


def _enc_consensus_params(cp: abci.ABCIConsensusParams) -> bytes:
    w = pw.Writer()
    if cp.block is not None:
        bw = pw.Writer()
        if cp.block.max_bytes:
            bw.varint(1, cp.block.max_bytes)
        if cp.block.max_gas:
            bw.varint(2, cp.block.max_gas)
        w.message(1, bw.finish())
    if cp.evidence is not None:
        ew = pw.Writer()
        if cp.evidence.max_age_num_blocks:
            ew.varint(1, cp.evidence.max_age_num_blocks)
        if cp.evidence.max_age_duration_ns:
            dw = pw.Writer()
            secs, nanos = divmod(cp.evidence.max_age_duration_ns, 1_000_000_000)
            if secs:
                dw.varint(1, secs)
            if nanos:
                dw.varint(2, nanos)
            ew.message(2, dw.finish())
        if cp.evidence.max_bytes:
            ew.varint(3, cp.evidence.max_bytes)
        w.message(2, ew.finish())
    if cp.validator is not None:
        vw = pw.Writer()
        for t in cp.validator.pub_key_types:
            vw.string(1, t)
        w.message(3, vw.finish())
    if cp.version is not None:
        vw = pw.Writer()
        if cp.version.app_version:
            vw.varint(1, cp.version.app_version)
        w.message(4, vw.finish())
    return w.finish()


def _dec_consensus_params(body: bytes) -> abci.ABCIConsensusParams:
    out = abci.ABCIConsensusParams()
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            b = abci.ABCIBlockParams()
            for bfn, _bwt, bv in pw.iter_fields(v):
                if bfn == 1:
                    b.max_bytes = pw.varint_to_int64(bv)
                elif bfn == 2:
                    b.max_gas = pw.varint_to_int64(bv)
            out.block = b
        elif fn == 2:
            e = abci.ABCIEvidenceParams()
            for efn, _ewt, ev in pw.iter_fields(v):
                if efn == 1:
                    e.max_age_num_blocks = pw.varint_to_int64(ev)
                elif efn == 2:
                    e.max_age_duration_ns = _dec_duration(ev)
                elif efn == 3:
                    e.max_bytes = pw.varint_to_int64(ev)
            out.evidence = e
        elif fn == 3:
            vp = abci.ABCIValidatorParams()
            for vfn, _vwt, vv in pw.iter_fields(v):
                if vfn == 1:
                    vp.pub_key_types.append(vv.decode())
            out.validator = vp
        elif fn == 4:
            ver = abci.ABCIVersionParams()
            for vfn, _vwt, vv in pw.iter_fields(v):
                if vfn == 1:
                    ver.app_version = pw.varint_to_int64(vv)
            out.version = ver
    return out


def _dec_duration(body: bytes) -> int:
    secs = nanos = 0
    for fn, _wt, v in pw.iter_fields(body):
        if fn == 1:
            secs = pw.varint_to_int64(v)
        elif fn == 2:
            nanos = pw.varint_to_int64(v)
    return secs * 1_000_000_000 + nanos


# --- per-message request codecs ---------------------------------------------

def _enc_request_body(method: str, req: Any) -> bytes:
    w = pw.Writer()
    if method == "echo":
        w.string(1, req)
    elif method in ("flush", "commit", "list_snapshots"):
        pass
    elif method == "info":
        if req.version:
            w.string(1, req.version)
        if req.block_version:
            w.varint(2, req.block_version)
        if req.p2p_version:
            w.varint(3, req.p2p_version)
    elif method == "init_chain":
        w.message(1, _ts_body(req.time_ns))
        w.string(2, req.chain_id)
        if req.consensus_params is not None:
            w.message(3, _enc_consensus_params(req.consensus_params))
        for vu in req.validators:
            w.message(4, _enc_validator_update(vu))
        if req.app_state_bytes:
            w.bytes(5, req.app_state_bytes)
        if req.initial_height:
            w.varint(6, req.initial_height)
    elif method == "query":
        if req.data:
            w.bytes(1, req.data)
        if req.path:
            w.string(2, req.path)
        if req.height:
            w.varint(3, req.height)
        if req.prove:
            w.bool(4, True)
    elif method == "begin_block":
        if req.hash:
            w.bytes(1, req.hash)
        if req.header is not None:
            w.message(2, req.header.encode())
        w.message(3, _enc_last_commit_info(req.last_commit_info))
        for e in req.byzantine_validators:
            w.message(4, _enc_evidence(e))
    elif method == "check_tx":
        if req.tx:
            w.bytes(1, req.tx)
        if req.type:
            w.varint(2, req.type)
    elif method == "deliver_tx":
        if req.tx:
            w.bytes(1, req.tx)
    elif method == "end_block":
        if req.height:
            w.varint(1, req.height)
    elif method == "offer_snapshot":
        if req.snapshot is not None:
            w.message(1, _enc_snapshot(req.snapshot))
        if req.app_hash:
            w.bytes(2, req.app_hash)
    elif method == "load_snapshot_chunk":
        if req.height:
            w.varint(1, req.height)
        if req.format:
            w.varint(2, req.format)
        if req.chunk:
            w.varint(3, req.chunk)
    elif method == "apply_snapshot_chunk":
        if req.index:
            w.varint(1, req.index)
        if req.chunk:
            w.bytes(2, req.chunk)
        if req.sender:
            w.string(3, req.sender)
    else:
        raise ValueError(f"unknown request method {method!r}")
    return w.finish()


def _dec_request_body(method: str, body: bytes) -> Any:
    f = pw.fields_dict(body) if body else {}

    def get(n, default=None):
        return f.get(n, [default])[0]

    if method == "echo":
        return (get(1, b"") or b"").decode()
    if method in ("flush", "commit", "list_snapshots"):
        return None
    if method == "info":
        return abci.RequestInfo(
            version=(get(1, b"") or b"").decode(),
            block_version=pw.varint_to_int64(get(2, 0) or 0),
            p2p_version=pw.varint_to_int64(get(3, 0) or 0))
    if method == "init_chain":
        return abci.RequestInitChain(
            time_ns=_dec_ts(get(1, b"") or b""),
            chain_id=(get(2, b"") or b"").decode(),
            consensus_params=(_dec_consensus_params(get(3))
                              if get(3) is not None else None),
            validators=[_dec_validator_update(v) for v in f.get(4, [])],
            app_state_bytes=get(5, b"") or b"",
            initial_height=pw.varint_to_int64(get(6, 0) or 0))
    if method == "query":
        return abci.RequestQuery(
            data=get(1, b"") or b"", path=(get(2, b"") or b"").decode(),
            height=pw.varint_to_int64(get(3, 0) or 0), prove=bool(get(4, 0)))
    if method == "begin_block":
        from ..types.block import Header

        hdr = Header.decode(get(2)) if get(2) is not None else None
        return abci.RequestBeginBlock(
            hash=get(1, b"") or b"", header=hdr,
            last_commit_info=_dec_last_commit_info(get(3, b"") or b""),
            byzantine_validators=[_dec_evidence(v) for v in f.get(4, [])])
    if method == "check_tx":
        return abci.RequestCheckTx(tx=get(1, b"") or b"",
                                   type=pw.varint_to_int64(get(2, 0) or 0))
    if method == "deliver_tx":
        return abci.RequestDeliverTx(tx=get(1, b"") or b"")
    if method == "end_block":
        return abci.RequestEndBlock(height=pw.varint_to_int64(get(1, 0) or 0))
    if method == "offer_snapshot":
        return abci.RequestOfferSnapshot(
            snapshot=_dec_snapshot(get(1)) if get(1) is not None else None,
            app_hash=get(2, b"") or b"")
    if method == "load_snapshot_chunk":
        return abci.RequestLoadSnapshotChunk(
            height=pw.varint_to_int64(get(1, 0) or 0),
            format=pw.varint_to_int64(get(2, 0) or 0),
            chunk=pw.varint_to_int64(get(3, 0) or 0))
    if method == "apply_snapshot_chunk":
        return abci.RequestApplySnapshotChunk(
            index=pw.varint_to_int64(get(1, 0) or 0),
            chunk=get(2, b"") or b"",
            sender=(get(3, b"") or b"").decode())
    raise ValueError(f"unknown request method {method!r}")


# --- per-message response codecs ---------------------------------------------

def _enc_tx_result_common(w: pw.Writer, r) -> None:
    if r.code:
        w.varint(1, r.code)
    if r.data:
        w.bytes(2, r.data)
    if r.log:
        w.string(3, r.log)
    if r.info:
        w.string(4, r.info)
    if r.gas_wanted:
        w.varint(5, r.gas_wanted)
    if r.gas_used:
        w.varint(6, r.gas_used)
    for ev in r.events:
        w.message(7, _enc_event(ev))
    if r.codespace:
        w.string(8, r.codespace)


def _enc_response_body(method: str, resp: Any) -> bytes:
    w = pw.Writer()
    if method == "exception":
        w.string(1, resp)
    elif method == "echo":
        w.string(1, resp)
    elif method == "flush":
        pass
    elif method == "info":
        if resp.data:
            w.string(1, resp.data)
        if resp.version:
            w.string(2, resp.version)
        if resp.app_version:
            w.varint(3, resp.app_version)
        if resp.last_block_height:
            w.varint(4, resp.last_block_height)
        if resp.last_block_app_hash:
            w.bytes(5, resp.last_block_app_hash)
    elif method == "init_chain":
        if resp.consensus_params is not None:
            w.message(1, _enc_consensus_params(resp.consensus_params))
        for vu in resp.validators:
            w.message(2, _enc_validator_update(vu))
        if resp.app_hash:
            w.bytes(3, resp.app_hash)
    elif method == "query":
        if resp.code:
            w.varint(1, resp.code)
        if resp.log:
            w.string(3, resp.log)
        if resp.info:
            w.string(4, resp.info)
        if resp.index:
            w.varint(5, resp.index)
        if resp.key:
            w.bytes(6, resp.key)
        if resp.value:
            w.bytes(7, resp.value)
        if resp.proof_ops:
            # tendermint.crypto.ProofOps{repeated ProofOp ops=1};
            # ProofOp{type=1 string, key=2, data=3}
            ops = pw.Writer()
            for op in resp.proof_ops:
                opw = pw.Writer()
                opw.string(1, op.type)
                opw.bytes(2, op.key)
                opw.bytes(3, op.data)
                ops.message(1, opw.finish())
            w.message(8, ops.finish())
        if resp.height:
            w.varint(9, resp.height)
        if resp.codespace:
            w.string(10, resp.codespace)
    elif method == "begin_block":
        for ev in resp.events:
            w.message(1, _enc_event(ev))
    elif method == "check_tx":
        _enc_tx_result_common(w, resp)
        if getattr(resp, "sender", ""):
            w.string(9, resp.sender)
        if getattr(resp, "priority", 0):
            w.varint(10, resp.priority)
        if getattr(resp, "mempool_error", ""):
            w.string(11, resp.mempool_error)
    elif method == "deliver_tx":
        _enc_tx_result_common(w, resp)
    elif method == "end_block":
        for vu in resp.validator_updates:
            w.message(1, _enc_validator_update(vu))
        if resp.consensus_param_updates is not None:
            w.message(2, _enc_consensus_params(resp.consensus_param_updates))
        for ev in resp.events:
            w.message(3, _enc_event(ev))
    elif method == "commit":
        if resp.data:
            w.bytes(2, resp.data)
        if resp.retain_height:
            w.varint(3, resp.retain_height)
    elif method == "list_snapshots":
        for s in resp.snapshots:
            w.message(1, _enc_snapshot(s))
    elif method == "offer_snapshot":
        if resp.result:
            w.varint(1, resp.result)
    elif method == "load_snapshot_chunk":
        if resp.chunk:
            w.bytes(1, resp.chunk)
    elif method == "apply_snapshot_chunk":
        if resp.result:
            w.varint(1, resp.result)
        for i in resp.refetch_chunks:
            w.varint(2, i)
        for s in resp.reject_senders:
            w.string(3, s)
    else:
        raise ValueError(f"unknown response method {method!r}")
    return w.finish()


def _dec_response_body(method: str, body: bytes) -> Any:
    f = pw.fields_dict(body) if body else {}

    def get(n, default=None):
        return f.get(n, [default])[0]

    def tx_common(cls):
        return cls(
            code=pw.varint_to_int64(get(1, 0) or 0), data=get(2, b"") or b"",
            log=(get(3, b"") or b"").decode(),
            info=(get(4, b"") or b"").decode(),
            gas_wanted=pw.varint_to_int64(get(5, 0) or 0),
            gas_used=pw.varint_to_int64(get(6, 0) or 0),
            events=[_dec_event(v) for v in f.get(7, [])],
            codespace=(get(8, b"") or b"").decode())

    if method == "exception":
        # callers raise their own error type on this
        return (get(1, b"") or b"").decode()
    if method == "echo":
        return (get(1, b"") or b"").decode()
    if method == "flush":
        return None
    if method == "info":
        return abci.ResponseInfo(
            data=(get(1, b"") or b"").decode(),
            version=(get(2, b"") or b"").decode(),
            app_version=pw.varint_to_int64(get(3, 0) or 0),
            last_block_height=pw.varint_to_int64(get(4, 0) or 0),
            last_block_app_hash=get(5, b"") or b"")
    if method == "init_chain":
        return abci.ResponseInitChain(
            consensus_params=(_dec_consensus_params(get(1))
                              if get(1) is not None else None),
            validators=[_dec_validator_update(v) for v in f.get(2, [])],
            app_hash=get(3, b"") or b"")
    if method == "query":
        proof_ops = None
        if get(8) is not None:
            from ..crypto.merkle import ProofOp

            proof_ops = []
            for opv in pw.fields_dict(get(8)).get(1, []):
                opf = pw.fields_dict(opv)
                proof_ops.append(ProofOp(
                    type=(opf.get(1, [b""])[0] or b"").decode(),
                    key=opf.get(2, [b""])[0] or b"",
                    data=opf.get(3, [b""])[0] or b""))
        return abci.ResponseQuery(
            code=pw.varint_to_int64(get(1, 0) or 0),
            log=(get(3, b"") or b"").decode(),
            info=(get(4, b"") or b"").decode(),
            index=pw.varint_to_int64(get(5, 0) or 0),
            key=get(6, b"") or b"", value=get(7, b"") or b"",
            proof_ops=proof_ops,
            height=pw.varint_to_int64(get(9, 0) or 0),
            codespace=(get(10, b"") or b"").decode())
    if method == "begin_block":
        return abci.ResponseBeginBlock(
            events=[_dec_event(v) for v in f.get(1, [])])
    if method == "check_tx":
        r = tx_common(abci.ResponseCheckTx)
        r.sender = (get(9, b"") or b"").decode()
        r.priority = pw.varint_to_int64(get(10, 0) or 0)
        r.mempool_error = (get(11, b"") or b"").decode()
        return r
    if method == "deliver_tx":
        return tx_common(abci.ResponseDeliverTx)
    if method == "end_block":
        return abci.ResponseEndBlock(
            validator_updates=[_dec_validator_update(v) for v in f.get(1, [])],
            consensus_param_updates=(_dec_consensus_params(get(2))
                                     if get(2) is not None else None),
            events=[_dec_event(v) for v in f.get(3, [])])
    if method == "commit":
        return abci.ResponseCommit(
            data=get(2, b"") or b"",
            retain_height=pw.varint_to_int64(get(3, 0) or 0))
    if method == "list_snapshots":
        return abci.ResponseListSnapshots(
            snapshots=[_dec_snapshot(v) for v in f.get(1, [])])
    if method == "offer_snapshot":
        return abci.ResponseOfferSnapshot(
            result=pw.varint_to_int64(get(1, 0) or 0))
    if method == "load_snapshot_chunk":
        return abci.ResponseLoadSnapshotChunk(chunk=get(1, b"") or b"")
    if method == "apply_snapshot_chunk":
        return abci.ResponseApplySnapshotChunk(
            result=pw.varint_to_int64(get(1, 0) or 0),
            refetch_chunks=[pw.varint_to_int64(v) for v in f.get(2, [])],
            reject_senders=[(v or b"").decode() for v in f.get(3, [])])
    raise ValueError(f"unknown response method {method!r}")


# --- envelopes + framing -----------------------------------------------------

def encode_request(method: str, req: Any) -> bytes:
    """uvarint-length-delimited Request envelope (socket_client.go framing)."""
    w = pw.Writer()
    w.message(REQ_FIELDS[method], _enc_request_body(method, req))
    return pw.length_delimited(w.finish())


def encode_response(method: str, resp: Any) -> bytes:
    w = pw.Writer()
    w.message(RESP_FIELDS[method], _enc_response_body(method, resp))
    return pw.length_delimited(w.finish())


def decode_request(body: bytes) -> Tuple[str, Any]:
    for fn, _wt, v in pw.iter_fields(body):
        method = REQ_BY_FIELD.get(fn)
        if method is None:
            raise ValueError(f"unknown request oneof field {fn}")
        return method, _dec_request_body(method, v)
    raise ValueError("empty ABCI request")


def decode_response(body: bytes) -> Tuple[str, Any]:
    for fn, _wt, v in pw.iter_fields(body):
        method = RESP_BY_FIELD.get(fn)
        if method is None:
            raise ValueError(f"unknown response oneof field {fn}")
        return method, _dec_response_body(method, v)
    raise ValueError("empty ABCI response")
