"""ABCI socket server: serves an Application over the reference's wire
format — uvarint-length-delimited protobuf Request/Response envelopes
(reference abci/server/socket_server.go:20, proto_codec.py) — so reference
tendermint nodes can drive apps served here.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from .application import Application
from .proto_codec import decode_request, encode_response
from .client import ABCIClientError, read_proto_frame


class ABCIServer:
    def __init__(self, addr: str, app: Application):
        self._addr = addr
        self._app = app
        # one mutex per server: every connection serializes into the app,
        # the reference's appMtx discipline (socket_server.go:32)
        self._app_mtx = threading.RLock()
        self._listener: Optional[socket.socket] = None
        self._threads = []
        self._stopped = threading.Event()

    def start(self) -> None:
        if self._addr.startswith("unix://"):
            path = self._addr[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._listener.bind(path)
        else:
            host, port = self._addr.replace("tcp://", "").rsplit(":", 1)
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, int(port)))
        self._listener.listen(16)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        while not self._stopped.is_set():
            try:
                body = read_proto_frame(conn)
            except (OSError, ABCIClientError):
                # malformed framing (oversized/overflowing varint) or socket
                # death: close so the peer sees EOF, not a hang
                try:
                    conn.close()
                except OSError:
                    pass
                return
            if body is None:
                return
            try:
                method, req = decode_request(body)
                with self._app_mtx:
                    resp = self._dispatch(method, req)
                conn.sendall(encode_response(method, resp))
            except Exception as e:  # report, don't kill the conn
                try:
                    conn.sendall(encode_response(
                        "exception", f"{type(e).__name__}: {e}"))
                except OSError:
                    return

    def _dispatch(self, method: str, req):
        if method == "echo":
            return req
        if method == "flush":
            return None
        if method in ("commit",):
            return self._app.commit()
        if method == "list_snapshots":
            from . import types as abci

            return self._app.list_snapshots(abci.RequestListSnapshots())
        return getattr(self._app, method)(req)

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
