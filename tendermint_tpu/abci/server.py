"""ABCI socket server: serves an Application to remote SocketClients
(reference abci/server/socket_server.go:20, with our JSON framing).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from .application import Application
from .client import _REQ_TYPES, _rebuild, _to_jsonable, read_frame, write_frame


class ABCIServer:
    def __init__(self, addr: str, app: Application):
        self._addr = addr
        self._app = app
        # one mutex per server: every connection serializes into the app,
        # the reference's appMtx discipline (socket_server.go:32)
        self._app_mtx = threading.RLock()
        self._listener: Optional[socket.socket] = None
        self._threads = []
        self._stopped = threading.Event()

    def start(self) -> None:
        if self._addr.startswith("unix://"):
            path = self._addr[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._listener.bind(path)
        else:
            host, port = self._addr.replace("tcp://", "").rsplit(":", 1)
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, int(port)))
        self._listener.listen(16)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        while not self._stopped.is_set():
            try:
                frame = read_frame(conn)
            except OSError:
                return
            if frame is None:
                return
            method = frame.get("method", "")
            try:
                with self._app_mtx:
                    resp = self._dispatch(method, frame.get("request"))
                write_frame(conn, {"response": _to_jsonable(resp)})
            except Exception as e:  # report, don't kill the conn
                write_frame(conn, {"error": f"{type(e).__name__}: {e}"})

    def _dispatch(self, method: str, raw_req):
        if method == "echo":
            return {"message": (raw_req or {}).get("message", "")}
        if method == "flush":
            return {}
        if method == "commit":
            return self._app.commit()
        req_cls = _REQ_TYPES.get(method)
        if req_cls is None:
            raise ValueError(f"unknown ABCI method {method!r}")
        req = _rebuild(req_cls, raw_req or {})
        return getattr(self._app, method)(req)

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
