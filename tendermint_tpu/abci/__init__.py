"""ABCI: the application boundary (reference abci/, SURVEY.md §2.6).

13 methods over 4 logical connections (consensus/mempool/query/snapshot).
"""

from .types import *  # noqa: F401,F403
from .application import Application  # noqa: F401
