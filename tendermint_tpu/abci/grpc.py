"""ABCI over gRPC — the third ABCI transport (reference
abci/client/grpc_client.go:22, abci/server/grpc_server.go:13).

Service ``tendermint.abci.ABCIApplication``: one unary RPC per ABCI method,
carrying the BARE RequestX/ResponseX protobuf bodies (not the oneof
envelope the socket transport frames). No generated stubs: grpcio's generic
handler API plus this package's hand-rolled gogoproto-exact codec
(proto_codec._enc_request_body/_dec_response_body) keep the wire identical
to the reference's generated types.pb.go.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional

import grpc

from . import types as abci
from .application import Application
from .client import Client
from .proto_codec import (
    _dec_request_body,
    _dec_response_body,
    _enc_request_body,
    _enc_response_body,
)

logger = logging.getLogger("tmtpu.abci.grpc")

SERVICE = "tendermint.abci.ABCIApplication"

# gRPC method name -> (codec method key, Application handler name)
_METHODS = {
    "Echo": ("echo", None),
    "Flush": ("flush", None),
    "Info": ("info", "info"),
    "DeliverTx": ("deliver_tx", "deliver_tx"),
    "CheckTx": ("check_tx", "check_tx"),
    "Query": ("query", "query"),
    "Commit": ("commit", "commit"),
    "InitChain": ("init_chain", "init_chain"),
    "BeginBlock": ("begin_block", "begin_block"),
    "EndBlock": ("end_block", "end_block"),
    "ListSnapshots": ("list_snapshots", "list_snapshots"),
    "OfferSnapshot": ("offer_snapshot", "offer_snapshot"),
    "LoadSnapshotChunk": ("load_snapshot_chunk", "load_snapshot_chunk"),
    "ApplySnapshotChunk": ("apply_snapshot_chunk", "apply_snapshot_chunk"),
}


class ABCIGrpcServer:
    """(grpc_server.go:13 NewServer) serves an Application over gRPC."""

    def __init__(self, addr: str, app: Application, max_workers: int = 4):
        self.app = app
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.bound_port = self._server.add_insecure_port(
            addr.split("://", 1)[-1])

    def _handler(self) -> grpc.GenericRpcHandler:
        app = self.app

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                path = handler_call_details.method  # /SERVICE/Method
                parts = path.rsplit("/", 2)
                if len(parts) != 3 or parts[1] != SERVICE:
                    return None
                grpc_name = parts[2]
                entry = _METHODS.get(grpc_name)
                if entry is None:
                    return None
                key, app_attr = entry

                def unary(req_bytes, context):
                    if key == "echo":
                        # RequestEcho{message=1} -> ResponseEcho{message=1}
                        req = _dec_request_body("echo", req_bytes)
                        return _enc_response_body("echo", req)
                    if key == "flush":
                        return _enc_response_body("flush", None)
                    req = _dec_request_body(key, req_bytes)
                    if key == "commit":
                        resp = app.commit()
                    else:
                        resp = getattr(app, app_attr)(req)
                    return _enc_response_body(key, resp)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        return Handler()

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace)


class GrpcClient(Client):
    """(grpc_client.go:22) the sync ABCI Client over a gRPC channel."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._channel = grpc.insecure_channel(addr.split("://", 1)[-1])
        self.timeout = timeout
        self._calls = {}
        for grpc_name, (key, _attr) in _METHODS.items():
            self._calls[key] = self._channel.unary_unary(
                f"/{SERVICE}/{grpc_name}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )

    def _call(self, key: str, req) -> object:
        body = _enc_request_body(key, req) if req is not None else b""
        resp = self._calls[key](body, timeout=self.timeout)
        return _dec_response_body(key, resp)

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def flush(self) -> None:
        self._call("flush", None)

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return self._call("info", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def begin_block(self, req):
        return self._call("begin_block", req)

    def deliver_tx(self, req):
        return self._call("deliver_tx", req)

    def end_block(self, req):
        return self._call("end_block", req)

    def commit(self) -> abci.ResponseCommit:
        return self._call("commit", None)

    def list_snapshots(self, req):
        return self._call("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)

    def close(self) -> None:
        self._channel.close()
