"""ABCI clients (reference abci/client/).

`LocalClient` wraps an in-process Application behind one mutex — the
reference's local_client.go:15 semantics (all connections share the lock).
`SocketClient` speaks the length-prefixed protobuf-free JSON framing of our
socket server (abci/server.py) for out-of-process apps.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from dataclasses import asdict, is_dataclass
from typing import Any, Callable, Optional

from . import types as abci
from .application import Application


class ABCIClientError(Exception):
    pass


class Client:
    """Synchronous call interface; async pipelining is layered above
    (state execution collects futures via callbacks)."""

    def echo(self, msg: str) -> str:
        raise NotImplementedError

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class LocalClient(Client):
    """In-proc app behind a shared mutex (abci/client/local_client.go:15)."""

    def __init__(self, app: Application, mtx: Optional[threading.RLock] = None):
        self._app = app
        self._mtx = mtx or threading.RLock()

    def echo(self, msg: str) -> str:
        return msg

    def info(self, req):
        with self._mtx:
            return self._app.info(req)

    def init_chain(self, req):
        with self._mtx:
            return self._app.init_chain(req)

    def query(self, req):
        with self._mtx:
            return self._app.query(req)

    def check_tx(self, req):
        with self._mtx:
            return self._app.check_tx(req)

    def begin_block(self, req):
        with self._mtx:
            return self._app.begin_block(req)

    def deliver_tx(self, req):
        with self._mtx:
            return self._app.deliver_tx(req)

    def end_block(self, req):
        with self._mtx:
            return self._app.end_block(req)

    def commit(self):
        with self._mtx:
            return self._app.commit()

    def list_snapshots(self, req):
        with self._mtx:
            return self._app.list_snapshots(req)

    def offer_snapshot(self, req):
        with self._mtx:
            return self._app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.apply_snapshot_chunk(req)


# --- wire helpers shared with abci/server.py -------------------------------

def _to_jsonable(obj: Any) -> Any:
    from ..types.block import Header

    if isinstance(obj, Header):
        # RequestBeginBlock.header crosses the socket as its proto encoding so
        # out-of-process apps see a real Header, same as in-process ones
        return {"__hdr": obj.encode().hex()}
    if is_dataclass(obj) and not isinstance(obj, type):
        # field-by-field (not asdict) so nested special types like Header
        # reach this function intact instead of pre-flattened to dicts
        return {name: _to_jsonable(getattr(obj, name))
                for name in obj.__dataclass_fields__}
    if isinstance(obj, bytes):
        return {"__b": obj.hex()}
    if isinstance(obj, list):
        return [_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    return obj


def _from_jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b"}:
            return bytes.fromhex(obj["__b"])
        if set(obj.keys()) == {"__hdr"}:
            from ..types.block import Header

            return Header.decode(bytes.fromhex(obj["__hdr"]))
        return {k: _from_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_jsonable(x) for x in obj]
    return obj


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf



def _rebuild(cls, data):
    """Shallow dataclass reconstruction — nested dataclasses rebuilt where typed."""
    if cls is None or data is None:
        return data
    import dataclasses
    import typing

    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        t = hints.get(f.name)
        origin = typing.get_origin(t)
        if origin is list and v is not None:
            (item_t,) = typing.get_args(t)
            if dataclasses.is_dataclass(item_t):
                v = [_rebuild(item_t, x) for x in v]
        elif dataclasses.is_dataclass(t) and isinstance(v, dict):
            v = _rebuild(t, v)
        elif origin is typing.Union and v is not None and isinstance(v, dict):
            args = [a for a in typing.get_args(t) if dataclasses.is_dataclass(a)]
            if args:
                v = _rebuild(args[0], v)
        kwargs[f.name] = v
    return cls(**kwargs)


def read_proto_frame(sock: socket.socket) -> Optional[bytes]:
    """One uvarint-length-delimited message body, or None on EOF."""
    length = 0
    shift = 0
    while True:
        b = _read_exact(sock, 1)
        if b is None:
            return None
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise ABCIClientError("varint length overflow")
    if length > 104857600:  # 100 MB sanity cap (socket framing guard)
        raise ABCIClientError(f"ABCI message too large: {length}")
    body = _read_exact(sock, length)
    if body is None:
        return None
    return body


class SocketClient(Client):
    """Out-of-process client speaking the reference's wire format: uvarint-
    length-delimited protobuf Request/Response envelopes with explicit flush
    (reference abci/client/socket_client.go:27) — wire-compatible with
    reference-built ABCI apps."""

    def __init__(self, addr: str):
        from .proto_codec import decode_response, encode_request

        self._addr = addr
        self._sock = _dial(addr)
        self._mtx = threading.Lock()
        self._encode_request = encode_request
        self._decode_response = decode_response

    def _call(self, method: str, req: Any = None) -> Any:
        with self._mtx:
            # request + flush, then read until this method's response arrives
            # (reference apps buffer responses until a flush)
            self._sock.sendall(self._encode_request(method, req)
                               + self._encode_request("flush", None))
            while True:
                body = read_proto_frame(self._sock)
                if body is None:
                    raise ABCIClientError(f"connection closed during {method}")
                got, resp = self._decode_response(body)
                if got == "exception":
                    raise ABCIClientError(resp)
                if got == method:
                    # drain the flush ack
                    fl = read_proto_frame(self._sock)
                    if fl is not None:
                        self._decode_response(fl)
                    return resp
                if got == "flush":
                    continue
                raise ABCIClientError(
                    f"unexpected {got!r} response to {method!r}")

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def info(self, req):
        return self._call("info", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def begin_block(self, req):
        return self._call("begin_block", req)

    def deliver_tx(self, req):
        return self._call("deliver_tx", req)

    def end_block(self, req):
        return self._call("end_block", req)

    def commit(self):
        return self._call("commit")

    def list_snapshots(self, req):
        return self._call("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _dial(addr: str) -> socket.socket:
    if addr.startswith("unix://"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(addr[len("unix://"):])
        return s
    host, port = addr.replace("tcp://", "").rsplit(":", 1)
    s = socket.create_connection((host, int(port)))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s
