"""Application interface — 13 methods (reference abci/types/application.go:11).

BaseApplication provides OK-everything defaults, like the reference's
abci/types/application.go BaseApplication.
"""

from __future__ import annotations

from . import types as abci


class Application:
    #: Optimistic parallel execution opt-in (state/parallel.py). An app
    #: that sets this True must implement the speculation protocol:
    #:
    #: ``spec_read(space, key)`` — read committed state for one logical
    #: key, with NO side effects (called concurrently, lock-free).
    #: ``deliver_tx_on_view(tx, view)`` — the pure-speculation twin of
    #: ``deliver_tx``: identical decision logic and response bytes, but
    #: every state access goes through the view (``read`` / ``write`` /
    #: ``emit`` / ``add``) instead of mutating the app.
    #: ``apply_spec_ops(ops)`` — replay one tx's recorded op log against
    #: real state (called under the app mutex, in block order).
    #:
    #: Invariant: for any tx and any state, ``deliver_tx_on_view`` +
    #: ``apply_spec_ops`` must leave the app byte-identical (state, app
    #: hash, response, events) to a plain ``deliver_tx`` — the parallel
    #: executor differential-tests this but cannot prove it for you.
    parallel_exec_supported = False

    # -- info/query connection --
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo()

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return abci.ResponseQuery()

    # -- mempool connection --
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return abci.ResponseCheckTx()

    # -- consensus connection --
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return abci.ResponseInitChain()

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return abci.ResponseDeliverTx()

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        return abci.ResponseCommit()

    # -- snapshot connection --
    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        return abci.ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        return abci.ResponseApplySnapshotChunk()

    def set_option(self, key: str, value: str) -> None:  # legacy SetOption
        pass
