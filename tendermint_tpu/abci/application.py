"""Application interface — 13 methods (reference abci/types/application.go:11).

BaseApplication provides OK-everything defaults, like the reference's
abci/types/application.go BaseApplication.
"""

from __future__ import annotations

from . import types as abci


class Application:
    # -- info/query connection --
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo()

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return abci.ResponseQuery()

    # -- mempool connection --
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return abci.ResponseCheckTx()

    # -- consensus connection --
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return abci.ResponseInitChain()

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return abci.ResponseDeliverTx()

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        return abci.ResponseCommit()

    # -- snapshot connection --
    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        return abci.ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        return abci.ResponseApplySnapshotChunk()

    def set_option(self, key: str, value: str) -> None:  # legacy SetOption
        pass
