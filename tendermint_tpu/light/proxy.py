"""Light proxy: a local JSON-RPC server that forwards to a full node and
VERIFIES everything verifiable against light-client state before answering
(reference light/proxy/proxy.go, light/rpc/client.go — the `light` CLI).

Verified routes: ``commit``, ``block``, ``validators`` (checked against a
light-client-verified header: header hash, data hash, validator hashes) and
``abci_query`` (the primary is forced to prove: its merkle ``ProofOps`` are
run through crypto/merkle.ProofRuntime against the light-client-verified
app hash at query-height+1 — reference light/rpc/client.go
ABCIQueryWithOptions). Forwarded as-is: ``status``, ``health``,
``genesis``, broadcast routes.
"""

from __future__ import annotations

import base64
import logging
from typing import Any, Dict, Optional

from aiohttp import web

from ..rpc.core import RPCError
from ..rpc.server import _rpc_response
from .client import LightClient
from .provider import _decode_signed_header, _decode_validators

logger = logging.getLogger("tmtpu.light.proxy")

FORWARD_ROUTES = [
    "health", "status", "genesis", "net_info", "abci_info",
    "broadcast_tx_sync", "broadcast_tx_async", "broadcast_tx_commit",
    "unconfirmed_txs", "num_unconfirmed_txs", "tx", "tx_search",
]
VERIFIED_ROUTES = ["commit", "block", "validators", "abci_query"]


class LightProxy:
    def __init__(self, client: LightClient, primary_rpc):
        self.lc = client
        self.rpc = primary_rpc  # rpc.client.HTTPClient to the primary
        self._runner: Optional[web.AppRunner] = None
        self.bound_port: Optional[int] = None

    # -- verified handlers ---------------------------------------------------

    async def _verified_block(self, height: int) -> Dict[str, Any]:
        doc = await self.rpc.block(height or None)
        h = int(doc["block"]["header"]["height"])
        lb = await self.lc.verify_light_block_at_height(h)
        got = _decode_signed_header(
            {"header": doc["block"]["header"],
             "commit": doc["block"]["last_commit"] or
             {"height": 0, "round": 0,
              "block_id": {"hash": "", "parts": {"total": 0, "hash": ""}},
              "signatures": []}})
        if got.header.hash() != lb.signed_header.header.hash():
            raise RPCError(-32603, "primary served a block whose header does "
                                   "not match the verified header")
        # data integrity: txs must hash to the verified header's data_hash
        from ..types.block import Data

        txs = [base64.b64decode(t) for t in doc["block"]["data"]["txs"]]
        if Data(txs=txs).hash() != lb.signed_header.header.data_hash:
            raise RPCError(-32603, "block data does not match verified "
                                   "data_hash")
        return doc

    async def _verified_commit(self, height: int) -> Dict[str, Any]:
        doc = await self.rpc.commit(height or None)
        sh = _decode_signed_header(doc["signed_header"])
        lb = await self.lc.verify_light_block_at_height(sh.header.height)
        if sh.header.hash() != lb.signed_header.header.hash():
            raise RPCError(-32603, "primary served a commit for an "
                                   "unverified header")
        return doc

    async def _verified_validators(self, height: int) -> Dict[str, Any]:
        doc = await self.rpc.validators(height or None, per_page=100)
        h = int(doc["block_height"])
        lb = await self.lc.verify_light_block_at_height(h)
        vals = _decode_validators(doc["validators"])
        # page through the full set (the server caps per_page at 100)
        total = int(doc["total"])
        page = 2
        while len(vals) < total:
            more = await self.rpc.validators(h, page=page, per_page=100)
            got = _decode_validators(more["validators"])
            if not got:
                break
            vals.extend(got)
            page += 1
        from ..types.validator_set import ValidatorSet

        if ValidatorSet(vals).hash() != lb.signed_header.header.validators_hash:
            raise RPCError(-32603, "primary served validators that do not "
                                   "hash to the verified header")
        return doc

    async def _verified_abci_query(self, params: Dict[str, Any]
                                   ) -> Dict[str, Any]:
        """(light/rpc/client.go ABCIQueryWithOptions) force prove=true on
        the primary; run the returned ProofOps against the light-verified
        app hash. AppHash(H+1) commits the query state at H."""
        from ..crypto.merkle import ProofOp, default_proof_runtime, key_path

        path = params.get("path") or ""
        data = bytes.fromhex(params.get("data") or "")
        doc = await self.rpc.abci_query(path, data,
                                        height=int(params.get("height") or 0),
                                        prove=True)
        resp = doc["response"]
        if int(resp.get("code") or 0) != 0:
            return doc  # app-level error: nothing to verify
        value = base64.b64decode(resp.get("value") or "")
        h = int(resp.get("height") or 0)
        if h <= 0:
            raise RPCError(-32603, "primary returned no query height")
        ops_doc = (resp.get("proofOps") or {}).get("ops") or []
        if not ops_doc:
            raise RPCError(-32603, "primary returned no proof for the query "
                                   "(absence proofs are not supported)")
        ops = [ProofOp(type=o["type"], key=base64.b64decode(o.get("key") or ""),
                       data=base64.b64decode(o.get("data") or ""))
               for o in ops_doc]
        lb = await self.lc.verify_light_block_at_height(h + 1)
        app_hash = lb.signed_header.header.app_hash
        try:
            default_proof_runtime().verify_value(
                ops, app_hash, key_path(resp_key := (base64.b64decode(
                    resp.get("key") or "") or data)), value)
        except ValueError as e:
            raise RPCError(-32603, f"query proof verification failed "
                                   f"for key {resp_key!r}: {e}")
        return doc

    # -- server --------------------------------------------------------------

    async def _dispatch(self, method: str, params: Dict[str, Any]):
        height = int(params.get("height") or 0)
        if method == "commit":
            return await self._verified_commit(height)
        if method == "block":
            return await self._verified_block(height)
        if method == "validators":
            return await self._verified_validators(height)
        if method == "abci_query":
            return await self._verified_abci_query(params)
        if method in FORWARD_ROUTES:
            return await self.rpc.call(method, **params)
        raise RPCError(-32601, f"method {method!r} not supported by the "
                               "light proxy")

    async def _handle(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                _rpc_response(None, error=RPCError(-32700, "parse error")))
        if not isinstance(body, dict):
            # batches are not proxied (each entry would need verification
            # context); answer with a structured error, not a 500
            return web.json_response(_rpc_response(
                None, error=RPCError(-32600,
                                     "light proxy accepts single requests only")))
        method = body.get("method", "")
        params = body.get("params") or {}
        try:
            result = await self._dispatch(method, params)
            return web.json_response(_rpc_response(body.get("id"), result))
        except RPCError as e:
            return web.json_response(_rpc_response(body.get("id"), error=e))
        except Exception as e:
            logger.exception("light proxy %s failed", method)
            return web.json_response(_rpc_response(
                body.get("id"), error=RPCError(-32603, str(e))))

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        app = web.Application()
        app.router.add_post("/", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.bound_port = (self._runner.addresses[0][1]
                           if self._runner.addresses else port)
        logger.info("light proxy on %s:%d", host, self.bound_port)
        return self.bound_port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
