"""Light client (reference light/): pure verifier, bisection client,
divergence detector, providers, trusted store. All commit verification rides
the batched device verifier through ValidatorSet.verify_commit_light*."""

from .verifier import (  # noqa: F401
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
    header_expired,
)
from .client import LightClient, TrustOptions  # noqa: F401
