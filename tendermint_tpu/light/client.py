"""Light client: trusted store + primary/witness providers, sequential and
skipping (bisection) verification, divergence detection
(reference light/client.go:133,613,706; light/detector.go).

Every commit verification inside runs on the batched device verifier via
ValidatorSet.verify_commit_light{,_trusting} — BASELINE config #3's hot
path.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import List, Optional

from ..libs.db import MemDB
from ..types.light_block import LightBlock
from .provider import Provider
from .store import LightStore
from .verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrNewValSetCantBeTrusted,
    LightError,
    header_expired,
    validate_trust_level,
    verify_adjacent,
    verify_non_adjacent,
)

logger = logging.getLogger("tmtpu.light")

DEFAULT_MAX_CLOCK_DRIFT_S = 10.0


class DivergenceError(LightError):
    """A witness disagrees with the primary about a verified header — a
    possible light-client attack (light/detector.go)."""

    def __init__(self, witness_id: str, height: int, primary_hash: bytes,
                 witness_hash: bytes):
        super().__init__(
            f"witness {witness_id} diverges at height {height}: "
            f"{witness_hash.hex()[:16]} != primary {primary_hash.hex()[:16]}")
        self.witness_id = witness_id
        self.height = height
        self.primary_hash = primary_hash
        self.witness_hash = witness_hash


@dataclass
class TrustOptions:
    """(light/client.go TrustOptions) the subjective-initialization root."""

    period_s: float
    height: int
    hash: bytes


class LightClient:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: List[Provider],
                 store: Optional[LightStore] = None,
                 trust_level=DEFAULT_TRUST_LEVEL,
                 max_clock_drift_s: float = DEFAULT_MAX_CLOCK_DRIFT_S,
                 skipping: bool = True,
                 scoreboard=None):
        validate_trust_level(trust_level)
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store or LightStore(MemDB())
        self.trust_level = trust_level
        self.max_clock_drift_s = max_clock_drift_s
        self.skipping = skipping
        self._initialized = False
        # untrusted-provider bookkeeping (libs/peerscore.PeerScoreboard):
        # a diverging witness is struck and, once banned, skipped on later
        # cross-checks; an unavailable one backs off. The statesync state
        # provider injects its scoreboard so witness lies land on the same
        # peer_bans_total{reason="divergence"} series chunk lies do.
        if scoreboard is None:
            from ..libs.peerscore import PeerScoreboard

            scoreboard = PeerScoreboard(name="light")
        self.scoreboard = scoreboard

    # -- initialization (light/client.go initializeWithTrustOptions) --------

    async def _initialize(self) -> None:
        if self._initialized:
            return
        if self.store.latest_height() >= self.trust_options.height:
            self._initialized = True
            return
        lb = await self.primary.light_block(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.signed_header.header.hash() != self.trust_options.hash:
            raise LightError(
                f"expected header hash {self.trust_options.hash.hex()} at trust "
                f"height, got {lb.signed_header.header.hash().hex()}")
        # 2/3 of that header's own validator set must have signed (subjective
        # root is checked as hard as any other header)
        lb.validator_set.verify_commit_light(
            self.chain_id, lb.signed_header.commit.block_id,
            lb.signed_header.header.height, lb.signed_header.commit)
        self.store.save(lb)
        self._initialized = True

    # -- public API ----------------------------------------------------------

    async def verify_light_block_at_height(self, height: int,
                                           now_ns: Optional[int] = None
                                           ) -> LightBlock:
        """(light/client.go:474 VerifyLightBlockAtHeight)"""
        now_ns = now_ns or time.time_ns()
        await self._initialize()
        got = self.store.get(height)
        if got is not None:
            return got
        new_lb = await self.primary.light_block(height)
        new_lb.validate_basic(self.chain_id)
        await self._verify_light_block(new_lb, now_ns)
        self.store.save(new_lb)
        await self._detect_divergence(new_lb, now_ns)
        return new_lb

    async def update(self, now_ns: Optional[int] = None) -> Optional[LightBlock]:
        """Verify the primary's latest header (light/client.go Update)."""
        now_ns = now_ns or time.time_ns()
        await self._initialize()
        latest = await self.primary.light_block(0)
        latest.validate_basic(self.chain_id)
        if latest.signed_header.header.height <= self.store.latest_height():
            return None
        await self._verify_light_block(latest, now_ns)
        self.store.save(latest)
        await self._detect_divergence(latest, now_ns)
        return latest

    # -- verification paths --------------------------------------------------

    async def _verify_light_block(self, new_lb: LightBlock, now_ns: int) -> None:
        latest = self.store.latest()
        if latest is None:
            raise LightError("store empty; initialization failed?")
        target_h = new_lb.signed_header.header.height
        if target_h < self.store.first_height():
            raise LightError(
                f"backwards verification below {self.store.first_height()} "
                "not supported yet")
        # choose the closest trusted block BELOW the target
        base = None
        for h in reversed(self.store.heights()):
            if h <= target_h:
                base = self.store.get(h)
                break
        if base is None:
            raise LightError("no trusted block below the target height")
        if self.skipping:
            await self._verify_skipping(base, new_lb, now_ns)
        else:
            await self._verify_sequential(base, new_lb, now_ns)

    async def _verify_sequential(self, trusted: LightBlock, new_lb: LightBlock,
                                 now_ns: int) -> None:
        """(light/client.go:613 verifySequential) — TPU-first: the whole
        range is fetched, then every commit signature across it rides ONE
        batched device call (verifier.verify_chain_batched)."""
        from .verifier import verify_chain_batched

        chain = []
        for h in range(trusted.signed_header.header.height + 1,
                       new_lb.signed_header.header.height):
            inter = await self.primary.light_block(h)
            inter.validate_basic(self.chain_id)
            chain.append(inter)
        chain.append(new_lb)
        verify_chain_batched(trusted, chain, self.trust_options.period_s,
                             now_ns, self.max_clock_drift_s, self.trust_level)
        for lb in chain[:-1]:
            self.store.save(lb)

    async def _verify_skipping(self, trusted: LightBlock, new_lb: LightBlock,
                               now_ns: int) -> None:
        """(light/client.go:706 verifySkipping) bisection: try to skip
        straight to the target; on ErrNewValSetCantBeTrusted, fetch the
        midpoint, verify it, and retry from there."""
        depth = 0
        pivots = [new_lb]
        while pivots:
            target = pivots[-1]
            try:
                if target.signed_header.header.height == \
                        trusted.signed_header.header.height + 1:
                    verify_adjacent(trusted.signed_header, target.signed_header,
                                    target.validator_set,
                                    self.trust_options.period_s, now_ns,
                                    self.max_clock_drift_s)
                else:
                    verify_non_adjacent(trusted.signed_header,
                                        trusted.validator_set,
                                        target.signed_header,
                                        target.validator_set,
                                        self.trust_options.period_s, now_ns,
                                        self.max_clock_drift_s,
                                        self.trust_level)
            except ErrNewValSetCantBeTrusted:
                depth += 1
                if depth > 60:
                    raise LightError("bisection exceeded max depth")
                mid = (trusted.signed_header.header.height
                       + target.signed_header.header.height) // 2
                if mid == trusted.signed_header.header.height:
                    raise LightError("bisection cannot make progress")
                mid_lb = await self.primary.light_block(mid)
                mid_lb.validate_basic(self.chain_id)
                pivots.append(mid_lb)
                continue
            # verified: this pivot becomes trusted, pop it
            self.store.save(target)
            trusted = target
            pivots.pop()

    # -- divergence detection (light/detector.go) ----------------------------

    async def _detect_divergence(self, verified: LightBlock, now_ns: int) -> None:
        h = verified.signed_header.header.height
        primary_hash = verified.signed_header.header.hash()
        for w in self.witnesses:
            if self.scoreboard.banned(w.id()):
                continue  # a proven liar's opinion is worthless either way
            try:
                wlb = await w.light_block(h)
            except Exception as e:
                # transient unavailability is NOT evidence of lying: skip
                # this round and retry at the next height — only proven
                # divergence (below) strikes the scoreboard, so a flaky
                # witness can never be banned into a zero-witness check
                logger.warning("witness %s unavailable at %d: %s", w.id(), h, e)
                continue
            whash = wlb.signed_header.header.hash()
            if whash == primary_hash:
                self.scoreboard.record_success(w.id())
            else:
                # conflicting header: report to the witness and raise; the
                # caller decides whether to switch primaries
                self.scoreboard.record_failure(w.id(), "divergence",
                                               severe=True)
                try:
                    await w.report_evidence(
                        {"type": "light-client-attack", "height": h,
                         "primary": primary_hash.hex(),
                         "witness": whash.hex()})
                except Exception:
                    pass
                raise DivergenceError(w.id(), h, primary_hash, whash)
