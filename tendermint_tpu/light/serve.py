"""Light-client serving plane: coalesced verification for thousands of
concurrent clients (the serving side of arXiv 2410.03347).

The node-side verifier is fast (one BatchVerifier stream call per commit),
but a population of light clients each asking "verify height H against my
trusted H0" would still cost one dispatch per client. This module turns
serving into the same micro-batching discipline the vote batcher and the
ingest plane use:

* ``VerifyCoalescer`` — admission-queues concurrent trusting-verify
  requests and flushes them on a deadline/size trigger as ONE batched
  device call (``crypto.batch.precompute`` over the union of candidate
  signatures, then a scalar-spec replay per request under the
  ``precomputed_verdicts`` contextvar — the verify_chain_batched pattern,
  so accept/reject is byte-identical to ``light/verifier.verify`` BY
  CONSTRUCTION, BLS aggregated commits included). Identical requests in a
  flush share one verification; a bounded verdict cache absorbs the
  steady-state where thousands of clients ask about the same heights.
* ``HeaderCache`` — bounded height-keyed LRU with *pinned* entries: a
  client bisecting trust from H0 to H will ask for the span's midpoints,
  so serving H with a declared trusted height prefetches and pins the
  ``bisection_skeleton`` heights; the second client through the same span
  hits memory.
* ``ClientLimiter`` — per-client token buckets with abuse scoring on the
  peerscore ledger; every shed is an explicit reason-labeled
  ``ShedError`` (surfaced as an RPC error), never a stall.
* ``ServeProvider`` + the ``lightserve.lying_server`` fault site — the
  chaos seam: an armed serving node swaps responses for an
  operator-supplied forged fork that only witness cross-check can catch.

The planning math at the top (flush schedule, bisection skeleton, fan-out
queue bounds) is pure stdlib with no package imports — loadable by file
path from ``tools/lightserve_bench.py --self-test``; everything touching
crypto/types imports lazily inside methods.
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: chaos seam consulted by every serving surface (ServeProvider and the
#: node's /light_header route): when armed and it fires, the served header
#: is swapped for a tampered/forged one. Registered in libs/faults.
TAMPER_SITE = "lightserve.lying_server"

_MISS = object()


# -- pure planning math ------------------------------------------------------
# (stdlib-only: tools/lightserve_bench.py loads this file standalone)

def bisection_skeleton(trusted_height: int, target_height: int,
                       cap: int = 64) -> List[int]:
    """Heights a bisecting client (light/client.py _verify_skipping) can ask
    for between trusted H0 and target H: breadth-first midpoints of the
    span, shallowest pivots first — the order bisection depth explores
    them. Bounded by ``cap``; deterministic pure math so serving planes and
    tools plan prefetch identically."""
    out: List[int] = []
    if target_height - trusted_height < 2:
        return out
    frontier = collections.deque([(trusted_height, target_height)])
    seen = set()
    while frontier and len(out) < cap:
        lo, hi = frontier.popleft()
        mid = (lo + hi) // 2
        if mid <= lo or mid >= hi or mid in seen:
            continue
        seen.add(mid)
        out.append(mid)
        frontier.append((lo, mid))
        frontier.append((mid, hi))
    return out


def plan_flushes(arrivals: List[float], deadline_s: float,
                 max_batch: int) -> List[Tuple[float, int]]:
    """Flush schedule for a sorted arrival series: a batch opens at its
    first request and closes when ``max_batch`` requests accumulate or
    ``deadline_s`` elapses, whichever first. Returns
    ``[(flush_time, batch_size)]`` — the pure spec ``VerifyCoalescer``
    implements and the bench self-test checks."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    if deadline_s < 0:
        raise ValueError("deadline_s must be >= 0")
    out: List[Tuple[float, int]] = []
    i, n = 0, len(arrivals)
    while i < n:
        t0 = arrivals[i]
        j = i + 1
        while j < n and j - i < max_batch and arrivals[j] <= t0 + deadline_s:
            j += 1
        t_flush = arrivals[j - 1] if j - i >= max_batch else t0 + deadline_s
        out.append((t_flush, j - i))
        i = j
    return out


def fanout_queue_plan(n_events: int, drained: int,
                      maxsize: int) -> Tuple[int, bool]:
    """Per-socket bounded send-queue math: ``n_events`` enqueued while the
    consumer drained ``drained`` of them -> (high-water mark, evicted?).
    A bounded queue EVICTS the socket on overflow (closes it with an
    explicit code) instead of stalling the event bus — the policy
    rpc/server._WsFanout implements."""
    if maxsize < 1:
        raise ValueError("maxsize must be >= 1")
    backlog = max(0, n_events - max(0, drained))
    return min(backlog, maxsize), backlog > maxsize


class TokenBucket:
    """Classic token bucket with an injectable clock (determinism seam)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()

    def allow(self, cost: float = 1.0) -> bool:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False


class ShedError(Exception):
    """An admission shed: always an explicit, reason-labeled rejection
    (never a stall). ``reason`` lands in the RPC error payload and the
    sheds metric label."""

    def __init__(self, reason: str):
        super().__init__(f"request shed ({reason})")
        self.reason = reason


class HeaderCache:
    """Bounded height-keyed cache with pinned bisection-skeleton entries.

    Plain entries evict LRU-first; pinned entries (prefetched bisection
    midpoints) are only sacrificed when every resident entry is pinned —
    capacity is a hard bound either way."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._entries: "collections.OrderedDict[int, Any]" = \
            collections.OrderedDict()
        self._pinned: set = set()
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}

    def __len__(self) -> int:
        return len(self._entries)

    def pinned_count(self) -> int:
        return len(self._pinned)

    def get(self, height: int):
        if height not in self._entries:
            self.stats["misses"] += 1
            return None
        self._entries.move_to_end(height)
        self.stats["hits"] += 1
        return self._entries[height]

    def peek(self, height: int):
        """get() without touching recency or hit/miss accounting (the
        prefetcher asking "is it already resident?")."""
        return self._entries.get(height)

    def put(self, height: int, value, pinned: bool = False) -> None:
        if height in self._entries:
            self._entries.move_to_end(height)
        self._entries[height] = value
        if pinned:
            self._pinned.add(height)
        while len(self._entries) > self.capacity:
            victim = next((h for h in self._entries
                           if h not in self._pinned), None)
            if victim is None:  # everything pinned: oldest pin goes
                victim = next(iter(self._entries))
            self._pinned.discard(victim)
            del self._entries[victim]
            self.stats["evictions"] += 1


class ClientLimiter:
    """Per-client token buckets + abuse scoring on the peerscore ledger.

    ``rate <= 0`` disables limiting entirely. A client that keeps hammering
    an empty bucket accumulates consecutive ``reason="rate"`` strikes on
    the scoreboard and gets banned (reason-labeled shed from then on);
    admitted requests record successes so honest bursts never accumulate.
    The scoreboard is duck-typed (record_failure/record_success/banned) so
    the pure self-tests can inject a stub."""

    def __init__(self, rate: float, burst: float, scoreboard=None,
                 max_clients: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.scoreboard = scoreboard
        self.max_clients = max_clients
        self._clock = clock
        self._buckets: "collections.OrderedDict[str, TokenBucket]" = \
            collections.OrderedDict()
        self.stats = {"admitted": 0, "rate_sheds": 0, "ban_sheds": 0}

    def admit(self, client_id: str) -> None:
        if self.rate <= 0:
            self.stats["admitted"] += 1
            return
        sb = self.scoreboard
        if sb is not None and sb.banned(client_id):
            self.stats["ban_sheds"] += 1
            raise ShedError("banned")
        bucket = self._buckets.get(client_id)
        if bucket is None:
            while len(self._buckets) >= self.max_clients:
                self._buckets.popitem(last=False)
            bucket = TokenBucket(self.rate, self.burst, self._clock)
            self._buckets[client_id] = bucket
        self._buckets.move_to_end(client_id)
        if not bucket.allow():
            self.stats["rate_sheds"] += 1
            if sb is not None:
                sb.record_failure(client_id, reason="rate")
            raise ShedError("client-rate")
        if sb is not None:
            sb.record_success(client_id)
        self.stats["admitted"] += 1


# -- the verification coalescer ----------------------------------------------

class VerifyRequest:
    """One light-client trusting-verify ask, exactly the arguments of
    ``light/verifier.verify``. ``cache_key`` (optional) marks the request
    dedupable: identical keys in a flush share one verification, and the
    verdict is remembered across flushes (callers only set it when the
    underlying content is immutable — canonical heights below the tip)."""

    __slots__ = ("trusted_sh", "trusted_vals", "untrusted_sh",
                 "untrusted_vals", "trusting_period_s", "now_ns",
                 "max_clock_drift_s", "trust_level", "cache_key")

    def __init__(self, trusted_sh, trusted_vals, untrusted_sh, untrusted_vals,
                 trusting_period_s: float, now_ns: int,
                 max_clock_drift_s: float,
                 trust_level: Tuple[int, int] = (1, 3), cache_key=None):
        self.trusted_sh = trusted_sh
        self.trusted_vals = trusted_vals
        self.untrusted_sh = untrusted_sh
        self.untrusted_vals = untrusted_vals
        self.trusting_period_s = trusting_period_s
        self.now_ns = now_ns
        self.max_clock_drift_s = max_clock_drift_s
        self.trust_level = trust_level
        self.cache_key = cache_key


class VerifyCoalescer:
    """Admission-queue concurrent verify requests; flush on deadline/size as
    ONE batched device call; resolve per-request futures from the shared
    verdict map.

    ``submit`` returns ``None`` (accepted) or the exact exception instance
    the scalar ``light/verifier.verify`` spec raises — the flush collects
    every candidate signature across the batch into one
    ``crypto.batch.precompute`` call and then replays the scalar spec per
    request under ``precomputed_verdicts``, so verdicts are byte-identical
    by construction (aggregated BLS commits skip collection and pair
    inline: a flush becomes a handful of pairings)."""

    def __init__(self, flush_deadline_s: float = 0.002, flush_max: int = 64,
                 queue_limit: int = 4096, verdict_cache_size: int = 4096,
                 backend: Optional[str] = None, metrics=None):
        if flush_max < 1:
            raise ValueError("flush_max must be >= 1")
        self.flush_deadline_s = flush_deadline_s
        self.flush_max = flush_max
        self.queue_limit = queue_limit
        self.verdict_cache_size = verdict_cache_size
        self.backend = backend
        self.metrics = metrics
        self._pending: List[Tuple[VerifyRequest, asyncio.Future]] = []
        self._inflight: Dict[Any, asyncio.Future] = {}
        self._timer: Optional[asyncio.Task] = None
        self._verdicts: "collections.OrderedDict[Any, Any]" = \
            collections.OrderedDict()
        self.stats = {"requests": 0, "flushes": 0, "largest_flush": 0,
                      "coalesced_dupes": 0, "verdict_cache_hits": 0,
                      "sheds": 0, "batched_sigs": 0, "verified_requests": 0}

    async def submit(self, req: VerifyRequest):
        self.stats["requests"] += 1
        key = req.cache_key
        if key is not None:
            hit = self._verdicts.get(key, _MISS)
            if hit is not _MISS:
                self._verdicts.move_to_end(key)
                self.stats["verdict_cache_hits"] += 1
                if self.metrics is not None:
                    self.metrics.verdict_cache_hits_total.inc()
                return hit
            inflight = self._inflight.get(key)
            if inflight is not None:
                self.stats["coalesced_dupes"] += 1
                return await asyncio.shield(inflight)
        if len(self._pending) >= self.queue_limit:
            self.stats["sheds"] += 1
            if self.metrics is not None:
                self.metrics.sheds_total.labels("queue-full").inc()
            raise ShedError("queue-full")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((req, fut))
        if key is not None:
            self._inflight[key] = fut
        if len(self._pending) >= self.flush_max:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            loop.create_task(self._flush())
        elif self._timer is None:
            self._timer = loop.create_task(self._deadline_flush())
        # shield: a cancelled client must not poison a future shared with
        # in-flight duplicates (or confuse the flush's set_result)
        return await asyncio.shield(fut)

    async def _deadline_flush(self) -> None:
        try:
            await asyncio.sleep(self.flush_deadline_s)
        except asyncio.CancelledError:
            return
        self._timer = None
        await self._flush()

    async def _flush(self) -> None:
        batch, self._pending = self._pending, []
        if not batch:
            return
        self.stats["flushes"] += 1
        self.stats["largest_flush"] = max(self.stats["largest_flush"],
                                          len(batch))
        if self.metrics is not None:
            self.metrics.flushes_total.inc()
            self.metrics.flush_occupancy.observe(len(batch))
        # within-flush dedup: identical cache keys share one verification
        groups: List[Tuple[VerifyRequest, List[asyncio.Future]]] = []
        by_key: Dict[Any, Tuple[VerifyRequest, List[asyncio.Future]]] = {}
        for req, fut in batch:
            g = by_key.get(req.cache_key) if req.cache_key is not None else None
            if g is not None:
                g[1].append(fut)
                self.stats["coalesced_dupes"] += 1
                continue
            g = (req, [fut])
            groups.append(g)
            if req.cache_key is not None:
                by_key[req.cache_key] = g
        reqs = [g[0] for g in groups]
        loop = asyncio.get_running_loop()
        try:
            results, nsigs = await loop.run_in_executor(
                None, self._verify_many, reqs)
        except Exception as e:  # defensive: never strand a future
            results, nsigs = [e] * len(reqs), 0
        self.stats["batched_sigs"] += nsigs
        self.stats["verified_requests"] += len(reqs)
        for (req, futs), res in zip(groups, results):
            if req.cache_key is not None:
                self._inflight.pop(req.cache_key, None)
                self._remember(req.cache_key, res)
            for fut in futs:
                if not fut.done():
                    fut.set_result(res)

    def _remember(self, key, res) -> None:
        self._verdicts[key] = res
        self._verdicts.move_to_end(key)
        while len(self._verdicts) > self.verdict_cache_size:
            self._verdicts.popitem(last=False)

    def _verify_many(self, reqs: List[VerifyRequest]):
        """Runs in a worker thread: one batched device call over the union
        of candidate signatures, then the scalar spec replayed per request.
        Returns ([None-or-exception per request], batched signature count)."""
        from ..crypto.batch import precompute, precomputed_verdicts
        from ..types.validator_set import _is_aggregated
        from .verifier import verify

        items = []
        seen = set()
        for r in reqs:
            commit = r.untrusted_sh.commit
            if _is_aggregated(commit):
                continue  # BLS aggregates pair inline in the scalar replay
            chain_id = r.trusted_sh.header.chain_id
            nvals = len(r.untrusted_vals.validators)
            for idx, cs in enumerate(commit.signatures):
                # malformed shapes are NOT pre-verified: the replay's
                # structural checks raise the same typed error as the
                # scalar path (its cache misses fall back to host verify)
                if not cs.for_block() or idx >= nvals:
                    continue
                pub = r.untrusted_vals.validators[idx].pub_key
                msg = commit.vote_sign_bytes(chain_id, idx)
                k = (pub.bytes(), msg, cs.signature)
                if k in seen:
                    continue
                seen.add(k)
                items.append((pub, msg, cs.signature))
        pre = precompute(items, plane="light",
                         backend=self.backend) if items else {}
        token = precomputed_verdicts.set(pre)
        try:
            out = []
            for r in reqs:
                try:
                    verify(r.trusted_sh, r.trusted_vals, r.untrusted_sh,
                           r.untrusted_vals, r.trusting_period_s, r.now_ns,
                           r.max_clock_drift_s, r.trust_level)
                    out.append(None)
                except Exception as e:
                    out.append(e)
        finally:
            precomputed_verdicts.reset(token)
        return out, len(items)

    def stop(self) -> None:
        """Cancel the deadline timer and fail anything still queued with an
        explicit shed (never a stall, even on shutdown)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._pending = self._pending, []
        for req, fut in batch:
            if req.cache_key is not None:
                self._inflight.pop(req.cache_key, None)
            if not fut.done():
                fut.set_exception(ShedError("shutdown"))
                # nobody may await a shut-down future; don't warn about it
                fut.exception()


# -- serving surfaces --------------------------------------------------------

class ServeProvider:
    """Light-block provider over a served chain — the adapter a LightClient
    fleet sees when it hits a serving node. Duck-types light/provider's
    Provider (light_block / report_evidence / id) without importing it so
    the module stays loadable standalone.

    Carries the ``lightserve.lying_server`` chaos seam: when the site is
    armed, ``forged`` is non-empty, and the site fires for a requested
    height, the response is swapped for the operator-supplied forged block
    (a re-signed fork that *verifies* — only witness cross-check catches
    it). HeaderCache-backed so the cell also exercises cache recency."""

    def __init__(self, chain_id: str, blocks: Dict[int, Any],
                 forged: Optional[Dict[int, Any]] = None,
                 name: str = "serve", cache_capacity: int = 256):
        self.chain_id = chain_id
        self.blocks = dict(blocks)
        self.forged = dict(forged or {})
        self.cache = HeaderCache(capacity=cache_capacity)
        self.evidence: List[Any] = []
        self._name = name

    async def light_block(self, height: int):
        if height == 0 and self.blocks:
            height = max(self.blocks)
        lb = self.cache.get(height)
        if lb is None:
            lb = self.blocks.get(height)
            if lb is None:
                from .provider import ErrLightBlockNotFound

                raise ErrLightBlockNotFound(
                    f"no light block at height {height}")
            self.cache.put(height, lb)
        if height in self.forged:
            from ..libs.faults import faults

            if faults.armed(TAMPER_SITE) and faults.fire(TAMPER_SITE):
                return self.forged[height]
        return lb

    async def report_evidence(self, ev) -> None:
        self.evidence.append(ev)

    def id(self) -> str:
        return self._name


class LightServePlane:
    """The node's serving plane: header/commit cache with bisection-aware
    prefetch, the verification coalescer, and per-client admission —
    behind the /light_header, /light_verify, /lightserve_status routes."""

    def __init__(self, *, block_store, state_store, chain_id: str,
                 config, metrics=None):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.cfg = config
        self.metrics = metrics
        self.cache = HeaderCache(capacity=config.cache_capacity)
        self.coalescer = VerifyCoalescer(
            flush_deadline_s=config.flush_deadline_ms / 1000.0,
            flush_max=config.flush_max,
            queue_limit=config.queue_limit,
            verdict_cache_size=config.verdict_cache_size,
            metrics=metrics)
        scoreboard = None
        if config.per_client_rate > 0:
            from ..libs.peerscore import PeerScoreboard

            scoreboard = PeerScoreboard(
                name="lightserve",
                ban_threshold=config.abuse_ban_threshold,
                bans_counter=(metrics.client_bans_total
                              if metrics is not None else None))
        self.scoreboard = scoreboard
        self.limiter = ClientLimiter(config.per_client_rate,
                                     config.per_client_burst,
                                     scoreboard=scoreboard)
        self.stats = {"headers_served": 0, "verifies_served": 0,
                      "prefetched": 0}

    # -- admission ----------------------------------------------------------

    def _admit(self, client_id: str, route: str) -> None:
        if self.metrics is not None:
            self.metrics.requests_total.labels(route).inc()
        try:
            self.limiter.admit(client_id or "anonymous")
        except ShedError as e:
            if self.metrics is not None:
                self.metrics.sheds_total.labels(e.reason).inc()
            raise

    # -- header serving -----------------------------------------------------

    def serve_header(self, height: int, trusted_height: int = 0,
                     client_id: str = "") -> Dict[str, Any]:
        """The /light_header answer: commit-route-shaped signed header doc.
        A declared ``trusted_height`` triggers bisection-skeleton prefetch
        for the span (pinned cache entries), so a fleet bisecting the same
        span hits memory. Raises ShedError on admission, KeyError when the
        height has no header."""
        self._admit(client_id, "light_header")
        tip = self.block_store.height()
        h = height or tip
        canonical = h != tip
        doc = None
        if canonical:
            doc = self.cache.get(h)
            if self.metrics is not None:
                if doc is not None:
                    self.metrics.cache_hits_total.inc()
                else:
                    self.metrics.cache_misses_total.inc()
        if doc is None:
            doc = self._build_doc(h, tip)
            if canonical:
                self.cache.put(h, doc)
        if trusted_height and 0 < trusted_height < h:
            self._prefetch_span(trusted_height, h)
        self.stats["headers_served"] += 1
        return self._maybe_tamper(doc)

    def _build_doc(self, h: int, tip: int) -> Dict[str, Any]:
        from ..rpc.json_enc import enc_commit, enc_header

        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise KeyError(f"no header at height {h}")
        if h == tip:
            commit = self.block_store.load_seen_commit(h)
            canonical = False
        else:
            commit = self.block_store.load_block_commit(h)
            canonical = True
        return {"signed_header": {"header": enc_header(meta.header),
                                  "commit": enc_commit(commit)},
                "canonical": canonical}

    def _prefetch_span(self, trusted_height: int, target_height: int) -> None:
        tip = self.block_store.height()
        for mid in bisection_skeleton(trusted_height, target_height,
                                      cap=self.cfg.prefetch_limit):
            if mid >= tip or self.cache.peek(mid) is not None:
                continue
            try:
                doc = self._build_doc(mid, tip)
            except KeyError:
                continue  # pruned height: nothing to pin
            self.cache.put(mid, doc, pinned=True)
            self.stats["prefetched"] += 1
            if self.metrics is not None:
                self.metrics.cache_prefetches_total.inc()

    def _maybe_tamper(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        from ..libs.faults import faults

        if not faults.armed(TAMPER_SITE) or not faults.fire(TAMPER_SITE):
            return doc
        import copy

        bad = copy.deepcopy(doc)
        hdr = bad["signed_header"]["header"]
        ah = hdr.get("app_hash") or "00" * 32
        hdr["app_hash"] = ("ff" if ah[:2] != "ff" else "00") + ah[2:]
        return bad

    # -- coalesced verification ---------------------------------------------

    async def serve_verify(self, height: int, trusted_height: int,
                           trust_level: Tuple[int, int] = (1, 3),
                           client_id: str = "") -> Optional[Exception]:
        """The /light_verify answer: trusting-verify ``height`` against
        ``trusted_height`` with the node's own stores as the header/valset
        source, through the coalescer. Returns None (accepted) or the exact
        scalar-spec exception."""
        self._admit(client_id, "light_verify")
        tip = self.block_store.height()
        if not (0 < trusted_height < height <= tip):
            raise KeyError(
                f"need 0 < trusted_height < height <= {tip}, "
                f"got trusted_height={trusted_height} height={height}")
        req = self._build_request(trusted_height, height, trust_level, tip)
        res = await self.coalescer.submit(req)
        self.stats["verifies_served"] += 1
        return res

    def _build_request(self, trusted_height: int, height: int,
                       trust_level: Tuple[int, int],
                       tip: int) -> VerifyRequest:
        from ..types.light_block import SignedHeader

        def signed_header(h: int) -> SignedHeader:
            meta = self.block_store.load_block_meta(h)
            if meta is None:
                raise KeyError(f"no header at height {h}")
            commit = (self.block_store.load_seen_commit(h) if h == tip
                      else self.block_store.load_block_commit(h))
            if commit is None:
                raise KeyError(f"no commit at height {h}")
            return SignedHeader(meta.header, commit)

        def vals(h: int):
            v = self.state_store.load_validators(h)
            if v is None:
                raise KeyError(f"no validator set at height {h}")
            return v

        now_ns = time.time_ns()
        # verdicts are only reusable while the content is immutable
        # (canonical heights below the tip) and within a trusting-period
        # bucket (expiry only moves one way; the minute bucket bounds how
        # stale a cached not-yet-expired verdict can be)
        cache_key = None
        if height < tip:
            cache_key = (trusted_height, height, trust_level,
                         now_ns // 60_000_000_000)
        return VerifyRequest(
            signed_header(trusted_height), vals(trusted_height),
            signed_header(height), vals(height),
            self.cfg.trusting_period_s, now_ns, self.cfg.max_clock_drift_s,
            trust_level, cache_key=cache_key)

    # -- observability / lifecycle ------------------------------------------

    def status(self) -> Dict[str, Any]:
        return {
            "served": dict(self.stats),
            "coalescer": dict(self.coalescer.stats),
            "cache": dict(self.cache.stats,
                          resident=len(self.cache),
                          pinned=self.cache.pinned_count()),
            "limiter": dict(self.limiter.stats),
        }

    def stop(self) -> None:
        self.coalescer.stop()
