"""Trusted light-block store on the DB abstraction
(reference light/store/db/db.go)."""

from __future__ import annotations

from typing import List, Optional

from ..libs.db import DB
from ..libs import protowire as pw
from ..types.light_block import LightBlock, SignedHeader
from ..types.validator_set import ValidatorSet

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + height.to_bytes(8, "big")


class LightStore:
    def __init__(self, db: DB):
        self.db = db

    def save(self, lb: LightBlock) -> None:
        w = pw.Writer()
        w.message(1, lb.signed_header.encode())
        w.message(2, lb.validator_set.encode())
        self.db.set(_key(lb.signed_header.header.height), w.finish())

    def get(self, height: int) -> Optional[LightBlock]:
        raw = self.db.get(_key(height))
        if raw is None:
            return None
        lb = LightBlock()
        for fn, _wt, v in pw.iter_fields(raw):
            if fn == 1:
                lb.signed_header = SignedHeader.decode(v)
            elif fn == 2:
                lb.validator_set = ValidatorSet.decode(v)
        return lb

    def latest_height(self) -> int:
        for k, _v in self.db.iterate(_PREFIX, _PREFIX + b"\xff", reverse=True):
            return int.from_bytes(k[len(_PREFIX):], "big")
        return 0

    def first_height(self) -> int:
        for k, _v in self.db.iterate(_PREFIX, _PREFIX + b"\xff"):
            return int.from_bytes(k[len(_PREFIX):], "big")
        return 0

    def latest(self) -> Optional[LightBlock]:
        h = self.latest_height()
        return self.get(h) if h else None

    def heights(self) -> List[int]:
        return [int.from_bytes(k[len(_PREFIX):], "big")
                for k, _ in self.db.iterate(_PREFIX, _PREFIX + b"\xff")]

    def prune(self, keep: int) -> None:
        hs = self.heights()
        for h in hs[:-keep] if keep else hs:
            self.db.delete(_key(h))
