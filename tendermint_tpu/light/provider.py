"""Light-block providers (reference light/provider/provider.go + http impl).

A provider serves LightBlocks (signed header + validator set) by height.
``HTTPProvider`` pulls from a full node's RPC (commit + validators routes);
``MockProvider`` serves a fixed map for tests.
"""

from __future__ import annotations

import base64
from typing import Dict, Optional

from ..crypto import Ed25519PubKey
from ..types.basic import BlockID, BlockIDFlag, PartSetHeader
from ..types.block import Commit, CommitSig, Consensus, Header
from ..types.light_block import LightBlock, SignedHeader
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    pass


class Provider:
    chain_id: str = ""

    async def light_block(self, height: int) -> LightBlock:
        """height == 0 means latest."""
        raise NotImplementedError

    async def report_evidence(self, ev) -> None:  # pragma: no cover - iface
        pass

    def id(self) -> str:
        return "provider"


class MockProvider(Provider):
    def __init__(self, chain_id: str, blocks: Dict[int, LightBlock]):
        self.chain_id = chain_id
        self.blocks = dict(blocks)
        self.evidence = []

    async def light_block(self, height: int) -> LightBlock:
        if height == 0 and self.blocks:
            height = max(self.blocks)
        lb = self.blocks.get(height)
        if lb is None:
            raise ErrLightBlockNotFound(f"no light block at height {height}")
        return lb

    async def report_evidence(self, ev) -> None:
        self.evidence.append(ev)

    def id(self) -> str:
        return f"mock-{id(self) & 0xffff:x}"


class HTTPProvider(Provider):
    """(light/provider/http) over the JSON-RPC client."""

    def __init__(self, chain_id: str, client):
        self.chain_id = chain_id
        self.client = client  # rpc.client.HTTPClient or LocalClient

    def id(self) -> str:
        return getattr(self.client, "base_url", "local")

    async def light_block(self, height: int) -> LightBlock:
        commit_doc = await self.client.commit(height or None)
        sh = _decode_signed_header(commit_doc["signed_header"])
        vals_doc = await self.client.validators(sh.header.height, per_page=100)
        vals = _decode_validators(vals_doc["validators"])
        total = int(vals_doc["total"])
        page = 2
        max_pages = -(-total // 100)  # ceil; a sane provider never needs more
        while len(vals) < total:
            if page > max_pages:
                raise ProviderError(
                    f"provider returned {len(vals)}/{total} validators "
                    f"after {max_pages} pages")
            more = await self.client.validators(sh.header.height, page=page,
                                                per_page=100)
            got = _decode_validators(more["validators"])
            if not got:
                raise ProviderError("provider returned an empty validator page")
            vals.extend(got)
            page += 1
        # priorities in the RPC answer are live: rebuild WITHOUT the
        # NewValidatorSet increment (validator_set.go
        # ValidatorSetFromExistingValidators) or proposer selection on a
        # statesync-bootstrapped node diverges from the network
        return LightBlock(sh, ValidatorSet.from_existing(vals))


# -- JSON -> domain decoding (inverse of rpc/json_enc.py) --------------------

def _decode_block_id(d) -> BlockID:
    return BlockID(bytes.fromhex(d["hash"]),
                   PartSetHeader(int(d["parts"]["total"]),
                                 bytes.fromhex(d["parts"]["hash"])))


def _parse_rfc3339_ns(s: str) -> int:
    """Inverse of json_enc.rfc3339: exact nanosecond round-trip."""
    import datetime

    if s.endswith("Z"):
        s = s[:-1]
    frac_ns = 0
    if "." in s:
        s, frac = s.split(".", 1)
        frac = frac[:9].ljust(9, "0")
        frac_ns = int(frac)
    dt = datetime.datetime.fromisoformat(s).replace(tzinfo=datetime.timezone.utc)
    return int(dt.timestamp()) * 1_000_000_000 + frac_ns


def _decode_header(d) -> Header:
    return Header(
        version=Consensus(int(d["version"]["block"]), int(d["version"]["app"])),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=_parse_rfc3339_ns(d["time"]),
        last_block_id=_decode_block_id(d["last_block_id"]),
        last_commit_hash=bytes.fromhex(d["last_commit_hash"]),
        data_hash=bytes.fromhex(d["data_hash"]),
        validators_hash=bytes.fromhex(d["validators_hash"]),
        next_validators_hash=bytes.fromhex(d["next_validators_hash"]),
        consensus_hash=bytes.fromhex(d["consensus_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        evidence_hash=bytes.fromhex(d["evidence_hash"]),
        proposer_address=bytes.fromhex(d["proposer_address"]),
    )


def _decode_signed_header(d) -> SignedHeader:
    c = d["commit"]
    commit = Commit(
        height=int(c["height"]), round=int(c["round"]),
        block_id=_decode_block_id(c["block_id"]),
        signatures=[
            CommitSig(BlockIDFlag(int(s["block_id_flag"])),
                      bytes.fromhex(s["validator_address"]),
                      _parse_rfc3339_ns(s["timestamp"]) if s["timestamp"] else 0,
                      base64.b64decode(s["signature"] or ""))
            for s in c["signatures"]
        ])
    return SignedHeader(_decode_header(d["header"]), commit)


def _decode_validators(lst) -> list:
    out = []
    for v in lst:
        pub = Ed25519PubKey(base64.b64decode(v["pub_key"]["value"]))
        out.append(Validator(bytes.fromhex(v["address"]), pub,
                             int(v["voting_power"]),
                             int(v.get("proposer_priority", 0))))
    return out
