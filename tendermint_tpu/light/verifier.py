"""Pure light-client verification (reference light/verifier.go:32,93).

Semantics mirror the reference exactly:

* verify_adjacent: trusting-period check, header/vals sanity, hash-chain
  (untrusted.ValidatorsHash == trusted.NextValidatorsHash), then
  VerifyCommitLight over the new set — which batches every present
  signature into one device call (types/validator_set.py);
* verify_non_adjacent: trusting-period check, header/vals sanity,
  VerifyCommitLightTrusting(trust_level, default 1/3) over the TRUSTED set,
  then VerifyCommitLight over the new set (ordered last deliberately — the
  untrusted set is attacker-supplied, reference verifier.go:70);
* verify_backwards: hash-linkage for walking the chain backwards.

Times are int nanoseconds; durations float seconds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..types.light_block import SignedHeader
from ..types.validator_set import Fraction, ValidatorSet

DEFAULT_TRUST_LEVEL = (1, 3)  # Fraction tuple


class LightError(Exception):
    pass


class ErrOldHeaderExpired(LightError):
    pass


class ErrInvalidHeader(LightError):
    pass


class ErrNewValSetCantBeTrusted(LightError):
    """< trust_level of the trusted set signed the new header — cannot skip;
    the caller bisects (light/client.go verifySkipping)."""


def validate_trust_level(lvl: Fraction) -> None:
    num, den = lvl
    if num * 3 < den or num > den or den == 0:
        raise LightError(f"trustLevel must be within [1/3, 1], given {lvl}")


def header_expired(h: SignedHeader, trusting_period_s: float, now_ns: int) -> bool:
    expiration_ns = h.header.time_ns + int(trusting_period_s * 1e9)
    return expiration_ns <= now_ns


def _verify_new_header_and_vals(untrusted: SignedHeader, untrusted_vals: ValidatorSet,
                                trusted: SignedHeader, now_ns: int,
                                max_clock_drift_s: float) -> None:
    untrusted.validate_basic(trusted.header.chain_id)
    if untrusted.header.height <= trusted.header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted.header.height} to be greater "
            f"than one of old header {trusted.header.height}")
    if untrusted.header.time_ns <= trusted.header.time_ns:
        raise ErrInvalidHeader(
            "expected new header time to be after old header time")
    if untrusted.header.time_ns >= now_ns + int(max_clock_drift_s * 1e9):
        raise ErrInvalidHeader("new header has a time from the future")
    if untrusted.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"expected new header validators "
            f"({untrusted.header.validators_hash.hex()}) to match those "
            f"supplied ({untrusted_vals.hash().hex()})")


def verify_adjacent(trusted: SignedHeader, untrusted: SignedHeader,
                    untrusted_vals: ValidatorSet, trusting_period_s: float,
                    now_ns: int, max_clock_drift_s: float) -> None:
    """(light/verifier.go:93)"""
    if untrusted.header.height != trusted.header.height + 1:
        raise LightError("headers must be adjacent in height")
    if header_expired(trusted, trusting_period_s, now_ns):
        raise ErrOldHeaderExpired("old header has expired")
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now_ns,
                                max_clock_drift_s)
    if untrusted.header.validators_hash != trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted.header.next_validators_hash.hex()}) to match those from "
            f"new header ({untrusted.header.validators_hash.hex()})")
    try:
        untrusted_vals.verify_commit_light(
            trusted.header.chain_id, untrusted.commit.block_id,
            untrusted.header.height, untrusted.commit)
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify_non_adjacent(trusted: SignedHeader, trusted_vals: ValidatorSet,
                        untrusted: SignedHeader, untrusted_vals: ValidatorSet,
                        trusting_period_s: float, now_ns: int,
                        max_clock_drift_s: float,
                        trust_level: Fraction = DEFAULT_TRUST_LEVEL) -> None:
    """(light/verifier.go:32)"""
    if untrusted.header.height == trusted.header.height + 1:
        raise LightError("headers must be non adjacent in height")
    if header_expired(trusted, trusting_period_s, now_ns):
        raise ErrOldHeaderExpired("old header has expired")
    _verify_new_header_and_vals(untrusted, untrusted_vals, trusted, now_ns,
                                max_clock_drift_s)
    from ..types.errors import ErrNotEnoughVotingPowerSigned

    try:
        # commit_vals: aggregated commits pair against the commit-height set
        # (the bitmap indexes into untrusted_vals); plain commits ignore it
        trusted_vals.verify_commit_light_trusting(
            trusted.header.chain_id, untrusted.commit, trust_level,
            commit_vals=untrusted_vals)
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e
    # last deliberately: untrusted set is attacker-sized (verifier.go:70)
    try:
        untrusted_vals.verify_commit_light(
            trusted.header.chain_id, untrusted.commit.block_id,
            untrusted.header.height, untrusted.commit)
    except Exception as e:
        raise ErrInvalidHeader(str(e)) from e


def verify(trusted: SignedHeader, trusted_vals: ValidatorSet,
           untrusted: SignedHeader, untrusted_vals: ValidatorSet,
           trusting_period_s: float, now_ns: int, max_clock_drift_s: float,
           trust_level: Fraction = DEFAULT_TRUST_LEVEL) -> None:
    """(light/verifier.go Verify) adjacent or skipping, by height gap."""
    if untrusted.header.height != trusted.header.height + 1:
        verify_non_adjacent(trusted, trusted_vals, untrusted, untrusted_vals,
                            trusting_period_s, now_ns, max_clock_drift_s,
                            trust_level)
    else:
        verify_adjacent(trusted, untrusted, untrusted_vals, trusting_period_s,
                        now_ns, max_clock_drift_s)


def verify_chain_batched(trusted_lb, chain, trusting_period_s: float,
                         now_ns: int, max_clock_drift_s: float,
                         trust_level: Fraction = DEFAULT_TRUST_LEVEL) -> None:
    """TPU-first chain verification: step trust through ``chain`` (a list of
    LightBlocks, ascending heights) with the SAME accept/reject semantics as
    calling :func:`verify` per step — but every signature check across every
    header rides ONE batched device call.

    Per-dispatch overhead dominates small commits (a 1000-validator commit is
    ~10 ms of device compute behind ~100 ms of relay dispatch), so the
    sequential light path (client verifySequential, statesync's h/h+1/h+2
    fetch, header-range proxies) batches the whole range. Raises the first
    failing step's error; header-rule checks stay strictly sequential.
    """
    from ..crypto.batch import BatchVerifier

    # one verification per unique (step, commit idx, pubkey); both the
    # trusting and light checks of a step share commit signatures
    bv = BatchVerifier(plane="light")
    positions = {}  # (step, commit idx) -> batch position
    for step, target in enumerate(chain):
        commit = target.signed_header.commit
        chain_id = trusted_lb.signed_header.header.chain_id
        # all for-block signatures; the trusting check's address-lookup keys
        # to the same pubkey bytes (address = hash(pubkey)), so both checks
        # hit this one verification
        nvals = len(target.validator_set.validators)
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block() or idx >= nvals:
                # malformed shapes are NOT pre-verified: the replay phase's
                # structural checks raise the same typed error as the
                # sequential path (its cache misses fall back to host verify)
                continue
            positions[(step, idx)] = len(positions)
            bv.add(target.validator_set.validators[idx].pub_key,
                   commit.vote_sign_bytes(chain_id, idx),
                   cs.signature)
    _, verdicts = bv.verify()

    # replay the exact sequential semantics; every signature check hits the
    # precomputed verdicts (crypto/batch.py contextvar) — zero extra dispatch
    pre = {}
    for (step, idx), pos in positions.items():
        commit = chain[step].signed_header.commit
        chain_id = trusted_lb.signed_header.header.chain_id
        target = chain[step]
        pre[(target.validator_set.validators[idx].pub_key.bytes(),
             commit.vote_sign_bytes(chain_id, idx),
             commit.signatures[idx].signature)] = bool(verdicts[pos])

    from ..crypto.batch import precomputed_verdicts

    token = precomputed_verdicts.set(pre)
    try:
        trusted = trusted_lb
        for target in chain:
            verify(trusted.signed_header, trusted.validator_set,
                   target.signed_header, target.validator_set,
                   trusting_period_s, now_ns, max_clock_drift_s, trust_level)
            trusted = target
    finally:
        precomputed_verdicts.reset(token)


def verify_backwards(untrusted, trusted) -> None:
    """(light/verifier.go:221) headers, untrusted.height == trusted.height-1."""
    untrusted.validate_basic()
    if untrusted.chain_id != trusted.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if untrusted.time_ns >= trusted.time_ns:
        raise ErrInvalidHeader(
            "expected older header time to be before new header time")
    if untrusted.hash() != trusted.last_block_id.hash:
        raise ErrInvalidHeader(
            f"older header hash {untrusted.hash().hex()} does not match "
            f"trusted header's last block {trusted.last_block_id.hash.hex()}")
