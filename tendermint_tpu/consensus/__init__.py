"""Consensus engine (reference consensus/, SURVEY.md §2.1).

Single-writer async state machine driving the one-height/many-round Tendermint
BFT protocol: NewRound → Propose → Prevote → PrevoteWait → Precommit →
PrecommitWait → Commit, with WAL-before-act crash recovery.
"""

from .config import ConsensusConfig  # noqa: F401
from .round_state import HeightVoteSet, RoundState, RoundStep  # noqa: F401
from .state import ConsensusState  # noqa: F401
from .wal import WAL, FsyncError, NilWAL  # noqa: F401
