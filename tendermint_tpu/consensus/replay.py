"""Crash recovery (reference consensus/replay.go).

Two mechanisms:
1. WAL catch-up replay (replay.go:93 catchupReplay): re-feed logged inputs for
   the in-flight height into the state machine before going live.
2. ABCI handshake (replay.go:200 Handshaker): replay blockstore blocks into
   the app until app height == store height.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..abci import types as abci
from ..abci.client import Client
from ..crypto import phases
from ..state import BlockExecutor, State, state_from_genesis
from ..state.execution import exec_commit_block, validator_update_to_validator
from ..state.store import StateStore
from ..store import BlockStore
from ..types import GenesisDoc
from ..types.basic import BlockID
from ..types.block import BLOCK_PROTOCOL
from ..types.event_bus import EventBus
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote
from .state import BlockPartMessage, ConsensusState, ProposalMessage, VoteMessage
from .wal import TimeoutInfo, WALMessage

logger = logging.getLogger("tmtpu.replay")


# --- WAL catch-up (replay.go:38-163) ---------------------------------------

def catchup_replay(cs: ConsensusState, height: int) -> int:
    """Replay WAL messages for `height` into the paused state machine;
    returns the number of records replayed (the recovery-plane metric
    wal_records_replayed)."""
    cs._replay_mode = True
    # replayed marks would be microseconds apart at replay time — not a
    # consensus-stage decomposition; the first live mark reopens the record
    cs.timeline.enabled = False
    try:
        if cs.wal.search_for_end_height(height):
            raise RuntimeError(
                f"WAL should not contain #ENDHEIGHT {height}; block {height} was "
                f"already committed — possible data corruption")
        msgs = cs.wal.messages_after_end_height(height - 1)
        for m in msgs:
            _replay_message(cs, m)
        return len(msgs)
    finally:
        cs._replay_mode = False
        cs.timeline.enabled = True


def _replay_message(cs: ConsensusState, m: WALMessage) -> None:
    """(replay.go:38 readReplayMessage semantics)"""
    if m.type == "round_step":
        return  # informational
    if m.type == "timeout":
        d = m.data
        cs._handle_timeout(TimeoutInfo(d["duration_s"], d["height"], d["round"], d["step"]))
        return
    if m.type == "vote":
        vote = Vote.decode(bytes.fromhex(m.data["vote"]))
        cs._try_add_vote(vote, m.data.get("peer", ""))
        return
    if m.type == "proposal":
        proposal = Proposal.decode(bytes.fromhex(m.data["proposal"]))
        try:
            cs._set_proposal(proposal)
        except ValueError as e:
            logger.debug("replay: proposal rejected: %s", e)
        return
    if m.type == "block_part":
        part = Part.decode(bytes.fromhex(m.data["part"]))
        msg = BlockPartMessage(m.data["height"], m.data["round"], part)
        added = cs._add_proposal_block_part(msg, m.data.get("peer", ""))
        if added and cs.rs.proposal_block_parts.is_complete():
            cs._handle_complete_proposal(msg.height)
        return
    if m.type == "end_height":
        return
    logger.warning("replay: unknown WAL message type %r", m.type)


# --- ABCI handshake (replay.go:200) ----------------------------------------

class Handshaker:
    def __init__(self, state_store: StateStore, state: State,
                 block_store: BlockStore, genesis: GenesisDoc,
                 event_bus: Optional[EventBus] = None, exec_config=None):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis = genesis
        self.event_bus = event_bus
        # the node's [execution] config: recovery's final apply_block goes
        # through the same executor version the live node will use, so a
        # crash mid-parallel-apply replays to the identical hash it would
        # have produced serially (state/parallel.py byte-parity invariant)
        self.exec_config = exec_config
        self.n_blocks = 0

    def handshake(self, proxy_app_consensus: Client, proxy_app_query: Client) -> State:
        """(replay.go:241 Handshake) — returns the possibly-updated state."""
        res = proxy_app_query.info(abci.RequestInfo(
            version="0.1.0-tpu", block_version=BLOCK_PROTOCOL, p2p_version=8))
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise ValueError(f"got a negative last block height ({app_height}) from the app")
        logger.info("ABCI handshake: app height=%d hash=%s", app_height, app_hash.hex())

        state = self.replay_blocks(self.initial_state, app_hash, app_height,
                                   proxy_app_consensus, proxy_app_query)
        logger.info("completed ABCI handshake; replayed %d blocks, app height now %d",
                    self.n_blocks, state.last_block_height)
        return state

    def replay_blocks(self, state: State, app_hash: bytes, app_block_height: int,
                      consensus_conn: Client, query_conn: Client) -> State:
        """(replay.go:284 ReplayBlocks)"""
        store_height = self.block_store.height()
        store_base = self.block_store.base()
        state_height = state.last_block_height

        # InitChain at genesis (replay.go:303-356)
        if app_block_height == 0:
            # pop rides along so an app that echoes the set back in
            # ResponseInitChain passes the bls12381 admission gate
            val_updates = [abci.ValidatorUpdate(v.pub_key.type_name,
                                                v.pub_key.bytes(),
                                                v.power, pop=v.pop)
                           for v in self.genesis.validators]
            params = state.consensus_params
            req = abci.RequestInitChain(
                time_ns=self.genesis.genesis_time_ns,
                chain_id=self.genesis.chain_id,
                consensus_params=None,
                validators=val_updates,
                app_state_bytes=self.genesis.app_state,
                initial_height=self.genesis.initial_height,
            )
            res = consensus_conn.init_chain(req)
            app_hash = res.app_hash or app_hash

            if state_height == 0:  # only apply initchain results if we're at genesis
                state = state.copy()
                state.app_hash = app_hash
                if res.validators:
                    # same admission rules as EndBlock updates — in
                    # particular the bls12381 proof-of-possession gate
                    from ..state.execution import validate_validator_updates

                    validate_validator_updates(res.validators,
                                               state.consensus_params)
                    vals = [validator_update_to_validator(vu) for vu in res.validators]
                    from ..types import ValidatorSet

                    state.validators = ValidatorSet(vals)
                    state.next_validators = state.validators.copy_increment_proposer_priority(1)
                elif not self.genesis.validators:
                    raise ValueError("validator set is nil in genesis and still empty after InitChain")
                self.state_store.save(state)

        # Figure out replay needs (replay.go:360-470)
        if store_height == 0:
            _assert_app_hash_eq(app_hash, state.app_hash)
            return state

        if store_height < app_block_height:
            raise ValueError(
                f"the app block height {app_block_height} is ahead of the store {store_height}")
        if store_height < state_height:
            raise ValueError(
                f"state height {state_height} is ahead of the store {store_height}")

        if store_height == state_height:
            # tendermint is in sync with itself; maybe replay into app
            if app_block_height < store_height:
                return self._replay_range(state, consensus_conn, query_conn,
                                          app_block_height, store_height, mutate_state=False)
            _assert_app_hash_eq(app_hash, state.app_hash)
            return state

        if store_height == state_height + 1:
            # we saved the block but crashed before ApplyBlock
            if app_block_height < state_height:
                # the app is further behind: replay up to state height then the final block
                state = self._replay_range(state, consensus_conn, query_conn,
                                           app_block_height, state_height, mutate_state=False)
                return self._apply_final_block(state, consensus_conn)
            if app_block_height == state_height:
                return self._apply_final_block(state, consensus_conn)
            if app_block_height == store_height:
                # app already has the final block; sync tendermint state
                block = self.block_store.load_block(store_height)
                from ..state.execution import update_state as _update_state
                # Re-derive state by applying block without re-executing txs:
                # exec responses were persisted before crash? If not, re-apply.
                return self._apply_final_block(state, consensus_conn)
        raise ValueError(
            f"uncovered state/store heights: state={state_height} store={store_height} "
            f"app={app_block_height}")

    def _replay_range(self, state: State, consensus_conn: Client, query_conn: Client,
                      app_block_height: int, final_height: int,
                      mutate_state: bool) -> State:
        """Replay blocks [app_height+1, final_height] into the app
        (replay.go:428 replayBlocks)."""
        first = app_block_height + 1
        if first == 1:
            first = state.initial_height
        for h in range(first, final_height + 1):
            logger.info("replaying block height=%d", h)
            block = self.block_store.load_block(h)
            # exec-plane segment per replayed block: handshake replay shows
            # up in the same phase breakdown as live apply (execution.py
            # tags its own), so recovery time decomposes like block time
            n_txs = len(block.data.txs)
            _seg = phases.Segment(sigs=n_txs, chunk=n_txs, device="app",
                                  plane="exec", height=h)
            _seg.begin()
            try:
                _seg.pack_done()
                exec_commit_block(consensus_conn, block, self.state_store,
                                  state.initial_height)
                _seg.dispatched()
            except BaseException:
                _seg.abandon()
                raise
            _seg.fetched()
            self.n_blocks += 1
        res = query_conn.info(abci.RequestInfo(version="0.1.0-tpu"))
        _assert_app_hash_eq(res.last_block_app_hash, state.app_hash)
        return state

    def _apply_final_block(self, state: State, consensus_conn: Client) -> State:
        """ApplyBlock for the stored-but-not-applied final block (replay.go:493)."""
        height = self.block_store.height()
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        from ..state.execution import BlockExecutor, EmptyEvidencePool, NoOpMempool

        block_exec = BlockExecutor(self.state_store, consensus_conn,
                                   NoOpMempool(), EmptyEvidencePool(),
                                   self.block_store, self.event_bus,
                                   exec_config=self.exec_config)
        state, _ = block_exec.apply_block(state, meta.block_id, block)
        self.n_blocks += 1
        return state


def _assert_app_hash_eq(app_hash: bytes, state_app_hash: bytes) -> None:
    """(replay.go:573 checkAppHash)"""
    if app_hash != state_app_hash:
        logger.warning("app hash (%s) does not match state app hash (%s)",
                       app_hash.hex(), state_app_hash.hex())
